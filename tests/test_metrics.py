"""Runtime telemetry (PR 4): tagged histograms + exposition format
properties, HBM/host memory accounting gauges, per-query resource
profiles (single-node and 2-node merge), cluster-wide /metrics
aggregation with breaker-aware degradation, the disabled-path nop
guarantee, structured JSON logging, and the promlint rules."""
import io
import json
import logging
import urllib.request

import pytest

from pilosa_tpu import SLICE_WIDTH, querystats, tracing
from pilosa_tpu import stats as stats_mod
from pilosa_tpu.server.server import Server
from pilosa_tpu.testing import ServerCluster


def http(method, url, body=None, ctype="application/json", headers=None):
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", ctype)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def promlint(text):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import promlint as pl
    finally:
        sys.path.pop(0)
    return pl.lint_text(text)


def sample_value(text, prefix):
    """Value of the first sample line starting with ``prefix``."""
    for ln in text.splitlines():
        if ln.startswith(prefix):
            return float(ln.rsplit(" ", 1)[1])
    raise AssertionError(f"no sample {prefix!r} in:\n{text}")


# ------------------------------------------------------ histogram unit


def test_histogram_buckets_and_exposition():
    h = stats_mod.Histogram("op_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 5.0):
        h.observe(v)
    lines = h.exposition_lines("pilosa_op_seconds")
    by = {ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
          for ln in lines}
    # le semantics: 0.01 lands IN the 0.01 bucket; cumulative counts.
    assert by['pilosa_op_seconds_bucket{le="0.01"}'] == 2
    assert by['pilosa_op_seconds_bucket{le="0.1"}'] == 3
    assert by['pilosa_op_seconds_bucket{le="1.0"}'] == 4
    assert by['pilosa_op_seconds_bucket{le="+Inf"}'] == 5
    assert by["pilosa_op_seconds_count"] == 5
    assert by["pilosa_op_seconds_sum"] == pytest.approx(5.565)


def test_histogram_tagged_children_share_family():
    h = stats_mod.Histogram("k_seconds", buckets=(0.5,))
    a = h.with_tags("kernel:count")
    b = h.with_tags("kernel:count")
    assert a is b            # memoized per tag set
    assert a is not h
    a.observe(0.1)
    h.observe(0.9)
    expo = stats_mod.prometheus_exposition({}, histograms=[h])
    # One TYPE line for the family even with tagged children present.
    assert expo.count("# TYPE pilosa_k_seconds histogram") == 1
    assert 'pilosa_k_seconds_bucket{kernel="count",le="0.5"} 1' in expo
    assert 'pilosa_k_seconds_bucket{le="+Inf"} 1' in expo
    assert not promlint(expo), promlint(expo)


def test_histogram_timer_and_nop():
    hset = stats_mod.HistogramSet()
    with hset.histogram("t_seconds").time():
        pass
    assert hset.histogram("t_seconds")._count == 1
    nop = stats_mod.NOP_HISTOGRAMS
    assert nop.histogram("anything") is stats_mod.NOP_HISTOGRAM
    assert not stats_mod.NOP_HISTOGRAM.enabled
    assert stats_mod.NOP_HISTOGRAM.with_tags("x") \
        is stats_mod.NOP_HISTOGRAM
    with stats_mod.NOP_HISTOGRAM.time():
        pass
    assert stats_mod.prometheus_exposition({}, histograms=nop) == "\n"


# --------------------------------------------- exposition properties


def test_exposition_every_line_parses_and_type_once():
    snap = {
        "plain_total": 3,
        "tagged_total;index:i": 1,
        "tagged_total;index:j,who:say \"hi\"": 2,
        "back\\slash;msg:a\\b": 1,
        "newline;msg:a\nb": 2,
        "nan_skipped": float("nan"),
        "inf_skipped": float("inf"),
        "bool_skipped": True,
        "str_skipped": "nope",
    }
    hset = stats_mod.HistogramSet(buckets=(0.1, 1.0))
    hset.histogram("lat_seconds").observe(0.05)
    hset.histogram("lat_seconds").with_tags("op:q").observe(3.0)
    out = stats_mod.prometheus_exposition(
        snap, namespaced=(("grp", {"x": 7, "y;peer:h": 1}),),
        histograms=hset)
    assert "nan_skipped" not in out and "inf_skipped" not in out
    assert out.count("# TYPE pilosa_tagged_total") == 1
    assert out.count("# TYPE pilosa_lat_seconds histogram") == 1
    assert 'pilosa_grp_y{peer="h"} 1' in out
    findings = promlint(out)
    assert not findings, findings
    # Families are contiguous: the tagged children of tagged_total sit
    # in one block under its single TYPE line.
    lines = out.splitlines()
    idx = [i for i, ln in enumerate(lines)
           if ln.startswith("pilosa_tagged_total")]
    assert idx == list(range(idx[0], idx[0] + 2))


def test_merge_expositions_node_label_and_errors():
    a = stats_mod.prometheus_exposition({"q_total": 1,
                                         "only_a": 2})
    b = stats_mod.prometheus_exposition({"q_total;index:i": 5})
    merged = stats_mod.merge_expositions(
        [("h1:1", a), ("h2:2", b)], scrape_errors={"h3:3": 4})
    assert merged.count("# TYPE pilosa_q_total") == 1
    assert 'pilosa_q_total{node="h1:1"} 1' in merged
    assert 'pilosa_q_total{node="h2:2",index="i"} 5' in merged
    assert ('pilosa_cluster_scrape_errors_total{node="h3:3"} 4'
            in merged)
    assert not promlint(merged), promlint(merged)


def test_parse_exposition_rejects_garbage():
    with pytest.raises(ValueError):
        stats_mod.parse_exposition("not a metric line at all{{{\n")


# ------------------------------------------------------- querystats


def test_querystats_scope_and_merge():
    assert querystats.active() is None
    qs = querystats.QueryStats()
    with querystats.scope(qs):
        querystats.add("slices", 3)
        assert querystats.active() is qs
    assert querystats.active() is None
    querystats.add("slices", 99)  # no active scope: dropped
    qs.merge({"slices": 2, "blocks": 7, "junk": "nope"})
    d = qs.to_dict()
    assert d["slices"] == 5 and d["blocks"] == 7
    assert "junk" not in d
    for key in querystats.KEYS:  # pre-seeded: profiles always complete
        assert key in d
    assert querystats.decode(querystats.encode(d)) == d
    assert querystats.decode("{broken") is None
    assert querystats.decode("[1]") is None


# ------------------------------------------------- single-node server


@pytest.fixture(scope="module")
def mserver(tmp_path_factory):
    s = Server(str(tmp_path_factory.mktemp("mx") / "d"),
               bind="127.0.0.1:0").open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    http("POST", f"{base}/index/i/frame/f", b"{}")
    for col in (1, 2, SLICE_WIDTH + 5):
        http("POST", f"{base}/index/i/query",
             f'SetBit(frame="f", rowID=1, columnID={col})'.encode())
    yield s, base
    s.close()


def test_memory_gauges_match_packed_bytes(mserver):
    s, base = mserver
    # A read faults the fragments in and builds device mirrors.
    status, body, _ = http("POST", f"{base}/index/i/query",
                           b'Count(Bitmap(frame="f", rowID=1))')
    assert status == 200 and json.loads(body)["results"] == [3]

    expected = 0
    for sl in (0, 1):
        frag = s.holder.fragment("i", "f", "standard", sl)
        assert frag is not None and frag._resident
        expected += int(frag._matrix.nbytes + frag._row_counts.nbytes)
    assert expected > 0

    text = http("GET", f"{base}/metrics")[1].decode()
    assert sample_value(
        text, 'pilosa_memory_fragment_bytes{index="i"}') == expected
    assert not promlint(text), promlint(text)

    mem = json.loads(http("GET", f"{base}/debug/memory")[1])
    assert mem["indexes"]["i"]["hostBytes"] == expected
    assert mem["indexes"]["i"]["residentFragments"] == 2
    assert mem["indexes"]["i"]["diskBytes"] > 0
    assert mem["indexes"]["i"]["deviceBytes"] > 0  # count built mirrors
    assert mem["governor"]["residentBytes"] >= expected
    assert "executor" in mem


def test_debug_vars_has_consistent_groups(mserver):
    _, base = mserver
    out = json.loads(http("GET", f"{base}/debug/vars")[1])
    assert out["qos"] == {"enabled": False}
    assert out["faults"]["enabled"] is False
    assert out["memory"]["indexes"]["i"]["fragments"] >= 2
    assert "histograms" in out  # default-on histogram set


def test_metrics_content_type_and_histogram_families(mserver):
    _, base = mserver
    status, body, headers = http("GET", f"{base}/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith(
        "text/plain; version=0.0.4")
    text = body.decode()
    # Executor latency histogram observed the fixture's queries.
    assert "# TYPE pilosa_executor_latency_seconds histogram" in text
    assert sample_value(
        text, "pilosa_executor_latency_seconds_count") >= 1
    # Kernel dispatch family exists (count kernels ran).
    assert "pilosa_kernel_dispatch_seconds_bucket" in text


def test_profile_resources_single_node(mserver):
    s, base = mserver
    s.executor._force_path = "serial"  # deterministic popcount path
    try:
        status, body, _ = http(
            "POST", f"{base}/index/i/query?profile=true",
            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        doc = json.loads(body)
        res = doc["profile"]["resources"]
        # Both slices of the index scanned, exactly once each.
        assert res["slices"] == s.holder.index("i").max_slice() + 1
        # The serial path charges its work either as popcounted bytes
        # (dense rows) or as container blocks with host-known counts
        # (the compressed tier serves Count with zero device work).
        assert (res["bytesPopcounted"] > 0
                or res["containerBlocksArray"] + res["containerBlocksRun"]
                + res["containerBlocksDense"] > 0)
        assert res["blocks"] >= 1
        assert res["fanoutCalls"] == 0
    finally:
        s.executor._force_path = None


def test_process_collector_gauges(mserver):
    s, base = mserver
    s._monitor_runtime()  # deterministic tick (monitor runs on timer)
    text = http("GET", f"{base}/metrics")[1].decode()
    assert sample_value(text, "pilosa_process_rss_bytes") > 0
    assert sample_value(text, "pilosa_process_threads") >= 1
    assert sample_value(text, "pilosa_process_uptime_seconds") >= 0
    assert "pilosa_process_gc_collections_total{generation=\"0\"}" \
        in text


def test_cluster_metrics_single_node(mserver):
    s, base = mserver
    text = http("GET", f"{base}/cluster/metrics")[1].decode()
    assert f'node="{s.host}"' in text
    assert not promlint(text), promlint(text)


# ----------------------------------------------- disabled path is nop


def test_histograms_off_is_nop(tmp_path):
    s = Server(str(tmp_path / "d"), bind="127.0.0.1:0",
               metrics={"histograms": False,
                        "collector-interval": 0}).open()
    try:
        assert s.histograms is stats_mod.NOP_HISTOGRAMS
        # The executor/client/handler hot paths hold the shared nop
        # objects: one `.enabled` attribute read, nothing else (the
        # qos.NOP / faults discipline).
        assert s.executor._hist_exec is stats_mod.NOP_HISTOGRAM
        assert s.executor._hist_round is stats_mod.NOP_HISTOGRAM
        assert s.client.histogram is stats_mod.NOP_HISTOGRAM
        assert s.handler.histograms is stats_mod.NOP_HISTOGRAMS
        base = f"http://{s.host}"
        http("POST", f"{base}/index/i", b"{}")
        http("POST", f"{base}/index/i/frame/f", b"{}")
        http("POST", f"{base}/index/i/query",
             b'SetBit(frame="f", rowID=1, columnID=2)')
        text = http("GET", f"{base}/metrics")[1].decode()
        assert "executor_latency_seconds" not in text
        assert "histogram" not in [
            ln.split()[-1] for ln in text.splitlines()
            if ln.startswith("# TYPE")]
    finally:
        s.close()


def test_cluster_metrics_disabled_403(tmp_path):
    s = Server(str(tmp_path / "d"), bind="127.0.0.1:0",
               metrics={"cluster-aggregation": False}).open()
    try:
        status, body, _ = http("GET",
                               f"http://{s.host}/cluster/metrics")
        assert status == 403
        assert "disabled" in json.loads(body)["error"]
        # Plain /metrics is untouched by the aggregation switch.
        assert http("GET", f"http://{s.host}/metrics")[0] == 200
    finally:
        s.close()


# ------------------------------------------------------ 2-node tests


@pytest.fixture(scope="module")
def cluster2():
    with ServerCluster(2, qos={"enabled": True}) as servers:
        s0, s1 = servers
        base = f"http://{s0.host}"
        http("POST", f"{base}/index/i", b"{}")
        http("POST", f"{base}/index/i/frame/f", b"{}")
        # Bits across 3 slices so both nodes own some.
        for col in (1, SLICE_WIDTH + 1, 2 * SLICE_WIDTH + 1):
            st, body, _ = http(
                "POST", f"{base}/index/i/query",
                f'SetBit(frame="f", rowID=7, columnID={col})'.encode())
            assert st == 200, body
        yield s0, s1


def test_profile_merges_worker_partials(cluster2):
    s0, s1 = cluster2
    for s in (s0, s1):
        s.executor._force_path = "serial"
    try:
        status, body, _ = http(
            "POST", f"http://{s0.host}/index/i/query?profile=true",
            b'Count(Bitmap(frame="f", rowID=7))')
        assert status == 200
        doc = json.loads(body)
        assert doc["results"] == [3]
        res = doc["profile"]["resources"]
        # Merged slice total == the index's slice count: every slice
        # scanned exactly once, across both nodes.
        assert res["slices"] == s0.holder.index("i").max_slice() + 1
        # Dense rows charge popcounted bytes; compressed rows charge
        # container blocks (Count is host-known there) — see the
        # single-node twin above.
        assert (res["bytesPopcounted"] > 0
                or res["containerBlocksArray"] + res["containerBlocksRun"]
                + res["containerBlocksDense"] > 0)
        assert res["blocks"] >= 1
        assert res["fanoutCalls"] >= 1
    finally:
        for s in (s0, s1):
            s.executor._force_path = None


def test_cluster_metrics_both_nodes_and_breaker_degradation(cluster2):
    s0, s1 = cluster2
    base = f"http://{s0.host}"
    status, body, headers = http("GET", f"{base}/cluster/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith(
        "text/plain; version=0.0.4")
    text = body.decode()
    assert f'node="{s0.host}"' in text
    assert f'node="{s1.host}"' in text
    assert not promlint(text), promlint(text)

    # Open the breaker for the peer: the aggregate degrades to a
    # partial result + scrape_errors sample — still HTTP 200.
    brk = s0.client.breakers
    for _ in range(brk.threshold):
        brk.record_failure(s1.host)
    assert brk.is_open(s1.host)
    try:
        status, body, _ = http("GET", f"{base}/cluster/metrics")
        assert status == 200
        text = body.decode()
        assert f'node="{s0.host}"' in text
        assert sample_value(
            text,
            f'pilosa_cluster_scrape_errors_total{{node="{s1.host}"}}'
        ) >= 1
        # The failure must NOT also surface misattributed to the
        # (healthy) coordinator via an untagged expvar counter.
        assert (f'pilosa_cluster_scrape_errors_total{{node="{s0.host}"'
                not in text)
        assert not promlint(text), promlint(text)
    finally:
        brk.record_success(s1.host)  # close for other tests


# ------------------------------------------------------ JSON logging


def test_json_log_format_stamps_trace_context():
    from pilosa_tpu.logfmt import JSONFormatter

    logger = logging.getLogger("pilosa_tpu.test_json_log")
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JSONFormatter())
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        tr = tracing.Tracer(ring_size=2)
        with tr.start("q") as root:
            logger.info("inside %s", "span")
        logger.info("outside")
        lines = [json.loads(ln) for ln in
                 stream.getvalue().strip().splitlines()]
        assert lines[0]["msg"] == "inside span"
        assert lines[0]["trace_id"] == root.trace.trace_id
        assert lines[0]["span_id"] == root.span_id
        assert lines[0]["level"] == "INFO"
        assert "trace_id" not in lines[1]
    finally:
        logger.removeHandler(handler)


def test_config_metrics_table_and_log_format(tmp_path):
    from pilosa_tpu.config import Config

    cfg = Config.load(overrides={
        "log-format": "json",
        "metrics": {"histograms": False, "collector-interval": 0,
                    "histogram-buckets": [0.01, 0.1, 1.0],
                    "cluster-aggregation": False}})
    assert cfg.log_format == "json"
    assert cfg.metrics["histograms"] is False
    toml = cfg.to_toml()
    assert 'log-format = "json"' in toml
    assert "[metrics]" in toml and "histogram-buckets = [0.01" in toml
    # Round trip: the emitted TOML loads back clean.
    p = tmp_path / "c.toml"
    p.write_text(toml)
    cfg2 = Config.load(str(p))
    assert cfg2.metrics["histogram-buckets"] == [0.01, 0.1, 1.0]
    assert cfg2.metrics["cluster-aggregation"] is False

    with pytest.raises(ValueError):
        Config.load(overrides={"log-format": "xml"})
    with pytest.raises(ValueError):
        Config.load(overrides={
            "metrics": {"histogram-buckets": [0.1, 0.1]}})
    with pytest.raises(ValueError):
        Config.load(overrides={"metrics": {"collector-interval": -1}})

    env = {"PILOSA_LOG_FORMAT": "json",
           "PILOSA_METRICS_HISTOGRAMS": "0",
           "PILOSA_METRICS_COLLECTOR_INTERVAL": "30"}
    cfg3 = Config.load(env=env)
    assert cfg3.log_format == "json"
    assert cfg3.metrics["histograms"] is False
    assert cfg3.metrics["collector-interval"] == 30
