"""Native C++ runtime parity tests: the ctypes-loaded codec/hashing must
be bit-identical to the pure-Python implementations, and every consumer
must work with the native layer force-disabled (fallback coverage)."""
import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.roaring import codec
from pilosa_tpu.utils.xxhash import _xxhash64_py, xxhash64

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_xxhash_parity(rng):
    for n in (0, 1, 3, 4, 7, 8, 13, 31, 32, 33, 100, 1024, 5000):
        data = rng.integers(0, 256, size=n).astype(np.uint8).tobytes()
        assert native.xxhash64(data, 0) == _xxhash64_py(data, 0), n
        assert native.xxhash64(data, 7) == _xxhash64_py(data, 7), n
    assert xxhash64(b"hello") == _xxhash64_py(b"hello")


def test_extract_positions(rng):
    words = rng.integers(0, 1 << 63, size=64, dtype=np.uint64)
    got = native.extract_positions(words, base=1000)
    want = np.flatnonzero(np.unpackbits(
        words.view(np.uint8), bitorder="little")).astype(np.uint64) + 1000
    assert np.array_equal(got, want)
    assert native.extract_positions(np.zeros(4, np.uint64)).size == 0


def _random_blocks(rng):
    def dense(density):
        bits = rng.random(codec.BITMAP_N * 64) < density
        return np.packbits(bits, bitorder="little").view(np.uint64)

    run_block = np.zeros(codec.BITMAP_N * 64, dtype=np.uint8)
    run_block[100:30000] = 1
    return {
        0: dense(0.001),                 # array container
        2: dense(0.4),                   # bitmap container
        9: np.packbits(run_block, bitorder="little").view(np.uint64),  # run
        (1 << 30): dense(0.01),
    }


def test_serialize_parity(rng, monkeypatch):
    blocks = _random_blocks(rng)
    native_bytes = codec.serialize(blocks)
    monkeypatch.setattr(native, "available", lambda: False)
    python_bytes = codec.serialize(blocks)
    assert native_bytes == python_bytes


def test_cross_deserialize(rng, monkeypatch):
    blocks = _random_blocks(rng)
    data = codec.serialize(blocks)  # native encoder
    ops = codec.op_record(codec.OP_ADD, (5 << 16) | 77)

    native_out, n_ops, torn = codec.deserialize(data + ops)
    monkeypatch.setattr(native, "available", lambda: False)
    python_out, n_ops2, torn2 = codec.deserialize(data + ops)

    assert (n_ops, torn) == (n_ops2, torn2) == (1, False)
    assert set(native_out) == set(python_out)
    for k in python_out:
        assert np.array_equal(native_out[k], python_out[k]), k


def test_native_rejects_corruption():
    with pytest.raises(ValueError, match="magic"):
        codec.deserialize(b"\x01\x02\x03\x04\x05\x06\x07\x08" * 2)


def test_fragment_with_python_fallback(tmp_path, monkeypatch):
    """Full fragment lifecycle must work without the native library."""
    monkeypatch.setattr(native, "available", lambda: False)
    from pilosa_tpu.storage.fragment import Fragment

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    # duplicate bits + same-word collisions exercise the sort/reduceat
    # OR-fold in the NumPy fallback path
    f.import_bits([0, 1, 0, 0, 1], [5, 6, 5, 7, 70])
    assert f.count() == 4
    assert f.row_count(0) == 2 and f.row_count(1) == 2
    assert [b for b, _ in f.blocks()] == [0]
    f.close()
    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    assert f2.count() == 4
    f2.close()


# ------------------------------------------------------- CSV + op batch

def test_parse_csv_matches_python():
    data = b"1,2\n3,4,1500000000\n\n10,20\r\n-5,7\n"
    got = native.parse_csv(data)
    assert got.tolist() == [[1, 2, 0], [3, 4, 1500000000],
                            [10, 20, 0], [-5, 7, 0]]


def test_parse_csv_spaces_and_signs():
    got = native.parse_csv(b" 1 , 2 \n+3,-4\n")
    assert got.tolist() == [[1, 2, 0], [3, -4, 0]]


def test_parse_csv_malformed_reports_line():
    import pytest
    with pytest.raises(ValueError, match="line 2"):
        native.parse_csv(b"1,2\n1,x\n")


def test_parse_csv_empty():
    assert native.parse_csv(b"").shape == (0, 3)
    assert native.parse_csv(b"\n\n").shape == (0, 3)


def test_encode_ops_matches_python_records():
    import numpy as np
    from pilosa_tpu.roaring import codec

    typs = np.array([codec.OP_ADD, codec.OP_REMOVE, codec.OP_ADD],
                    dtype=np.uint8)
    vals = np.array([0, 123456789, 2**63 + 5], dtype=np.uint64)
    got = native.encode_ops(typs, vals)
    want = b"".join(codec.op_record(int(t), int(v))
                    for t, v in zip(typs, vals))
    assert got == want
    # and the decoder round-trips it
    assert list(codec.read_ops(got)) == [
        (int(t), int(v)) for t, v in zip(typs, vals)]


def test_parse_csv_trailing_comma_rejected():
    import pytest
    with pytest.raises(ValueError, match="line 1"):
        native.parse_csv(b"1,2,\n")


def test_parse_csv_overflow_rejected():
    import pytest
    with pytest.raises(ValueError, match="line 1"):
        native.parse_csv(b"99999999999999999999,1\n")
    # INT64_MAX itself is accepted
    got = native.parse_csv(b"9223372036854775807,1\n")
    assert got[0, 0] == 2**63 - 1


def test_scatter_or_matches_numpy_reference():
    import numpy as np

    rng = np.random.default_rng(11)
    W = 64
    m = np.zeros((8, W), dtype=np.uint64)
    phys = rng.integers(0, 8, size=5000, dtype=np.int64)
    cols = rng.integers(0, W * 64, size=5000, dtype=np.uint64)
    assert native.scatter_or(m, phys, cols)

    want = np.zeros_like(m)
    for p, c in zip(phys, cols):
        want[p, int(c) >> 6] |= np.uint64(1) << np.uint64(int(c) & 63)
    assert (m == want).all()


def test_popcount_rows_matches_numpy():
    import numpy as np

    rng = np.random.default_rng(12)
    m = rng.integers(0, 2**63, size=(16, 128), dtype=np.uint64)
    rows = [0, 3, 15, 3]
    got = native.popcount_rows(m, rows)
    want = np.bitwise_count(m[rows]).sum(axis=-1, dtype=np.int64)
    assert got.tolist() == want.tolist()


def test_scatter_or_noncontiguous_falls_back():
    import numpy as np

    m = np.zeros((4, 128), dtype=np.uint64)[:, ::2]
    assert not native.scatter_or(m, np.array([0]), np.array([0],
                                                           dtype=np.uint64))


def test_scatter_or_wrong_dtype_falls_back():
    import numpy as np

    m32 = np.zeros((4, 256), dtype=np.uint32)  # device-mirror layout
    assert not native.scatter_or(m32, np.array([0]),
                                 np.array([0], dtype=np.uint64))
    assert native.popcount_rows(m32, [0]) is None
