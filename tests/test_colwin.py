"""Window-aware device stacks: batched executor paths allocate HBM at
the plan's column window, not the full 32,768-word slice.

The reference's containers never materialize empty column space
(roaring.go:1011-1024); round 2 brought that economy to HOST rows
(fragment column windows) but every device stack was still padded to
full slice width — ~256× HBM waste on narrow data (e.g. 120-bit
chemistry fingerprints). These tests pin the negotiated-window batched
paths: correctness against the serial path on low/high/mixed column
clusters, and the HBM-bytes bound device stacks must now satisfy.
"""
import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH, WORDS_PER_SLICE
from pilosa_tpu.executor import Executor
from pilosa_tpu.storage.holder import Holder


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    idx = holder.create_index("i")
    idx.create_frame("general")
    e = Executor(holder)
    e._force_path = "batched"
    serial = Executor(holder)
    serial._force_path = "serial"
    yield holder, idx, e, serial
    holder.close()


def _stack_widths(e):
    with e._cache_mu:
        return [entry[1].shape[-1] for entry in e._stack_cache.values()]


def _stack_bytes(e):
    with e._cache_mu:
        return sum(entry[2] for entry in e._stack_cache.values())


def _fill_cluster(frame, rows, n_slices, col_lo, col_hi):
    """Set bits for each row in [col_lo, col_hi) of every slice."""
    for s in range(n_slices):
        base = s * SLICE_WIDTH
        for r in rows:
            cols = list(range(base + col_lo, base + col_hi))
            frame.import_bits([r] * len(cols), cols)


def test_narrow_count_uses_narrow_stacks(env):
    holder, idx, e, serial = env
    frame = idx.frame("general")
    _fill_cluster(frame, [1, 2], n_slices=8, col_lo=0, col_hi=120)

    q = ('Count(Intersect(Bitmap(frame="general", rowID=1), '
         'Bitmap(frame="general", rowID=2)))')
    got = e.execute("i", q)[0]
    assert got == serial.execute("i", q)[0] == 8 * 120
    widths = _stack_widths(e)
    assert widths and all(w == Executor.MIN_WIN32 for w in widths), widths


def test_high_cluster_rebases_correctly(env):
    """Bits clustered at the END of the slice: the window base is
    nonzero and every device word must be rebased both directions."""
    holder, idx, e, serial = env
    frame = idx.frame("general")
    lo, hi = SLICE_WIDTH - 130, SLICE_WIDTH - 3
    _fill_cluster(frame, [1], n_slices=4, col_lo=lo, col_hi=hi)
    _fill_cluster(frame, [2], n_slices=4, col_lo=lo + 5, col_hi=hi + 2)

    q = ('Count(Intersect(Bitmap(frame="general", rowID=1), '
         'Bitmap(frame="general", rowID=2)))')
    got = e.execute("i", q)[0]
    assert got == serial.execute("i", q)[0] == 4 * (hi - (lo + 5))
    widths = _stack_widths(e)
    assert widths and all(w < WORDS_PER_SLICE for w in widths), widths

    # Bitmap materialization: columns must come back at their TRUE
    # global positions despite the windowed (rebased) device stack.
    qb = ('Intersect(Bitmap(frame="general", rowID=1), '
          'Bitmap(frame="general", rowID=2))')
    got_cols = e.execute("i", qb)[0].columns().tolist()
    want_cols = serial.execute("i", qb)[0].columns().tolist()
    assert got_cols == want_cols
    assert got_cols[0] == lo + 5 and got_cols[-1] == 3 * SLICE_WIDTH + hi - 1


def test_mixed_clusters_widen_window(env):
    """One row clustered low, one high: the union window must cover
    both (possibly full width) and stay correct."""
    holder, idx, e, serial = env
    frame = idx.frame("general")
    _fill_cluster(frame, [1], n_slices=2, col_lo=0, col_hi=64)
    _fill_cluster(frame, [2], n_slices=2, col_lo=SLICE_WIDTH - 64,
                  col_hi=SLICE_WIDTH)
    for q in (
        'Count(Union(Bitmap(frame="general", rowID=1), '
        'Bitmap(frame="general", rowID=2)))',
        'Count(Intersect(Bitmap(frame="general", rowID=1), '
        'Bitmap(frame="general", rowID=2)))',
    ):
        assert e.execute("i", q)[0] == serial.execute("i", q)[0]


def test_chem_shape_device_bytes_bounded(env):
    """The chem-showcase shape (many columns, 120-bit rows → narrow
    column window per slice? no — 120 ROWS of fingerprint bits over
    a narrow molecule-column span): device stack bytes must be ≤ 2×
    the host window bytes instead of 256× (VERDICT r2 'weak' #2)."""
    holder, idx, e, serial = env
    frame = idx.frame("general")
    n_slices = 8
    rng = np.random.default_rng(7)
    # 3 fingerprint-bit rows over a 2,000-molecule column cluster.
    for s in range(n_slices):
        base = s * SLICE_WIDTH
        for r in (0, 1, 2):
            cols = base + rng.choice(2000, size=400, replace=False)
            frame.import_bits([r] * len(cols), cols.tolist())

    q = ('Count(Intersect(Bitmap(frame="general", rowID=0), '
         'Bitmap(frame="general", rowID=1)))')
    assert e.execute("i", q)[0] == serial.execute("i", q)[0]

    dev_bytes = _stack_bytes(e)
    assert dev_bytes > 0
    host_window_bytes = 0
    view = "standard"
    for s in range(n_slices):
        frag = holder.fragment("i", "general", view, s)
        win = frag.win32()
        assert win is not None
        # 2 rows per stack entry (rowID 0 and 1), window width in
        # uint32 words × 4 bytes.
        host_window_bytes += 2 * win[1] * 4
    assert dev_bytes <= 2 * host_window_bytes, (
        dev_bytes, host_window_bytes)
    # And nowhere near the full-width allocation it used to make.
    full_width_bytes = 2 * n_slices * WORDS_PER_SLICE * 4
    assert dev_bytes <= full_width_bytes // 8


def test_bsi_sum_min_max_windowed(env):
    """BSI aggregates ride the windowed planes stack; results must
    match the serial path on clustered columns."""
    holder, idx, e, serial = env
    idx.frame("general")
    from pilosa_tpu.storage.frame import Field
    from pilosa_tpu.storage.index import FrameOptions

    idx.create_frame("f", FrameOptions(
        range_enabled=True,
        fields=[Field(name="v", type="int", min=0, max=1000)]))
    frame = idx.frame("f")
    base = SLICE_WIDTH - 500  # high cluster
    for i in range(200):
        frame.set_field_value(base + i, "v", (i * 7) % 1000)
    for q, want in (
        ('Sum(frame="f", field="v")',
         sum((i * 7) % 1000 for i in range(200))),
        ('Min(frame="f", field="v")', 0),
        ('Max(frame="f", field="v")',
         max((i * 7) % 1000 for i in range(200))),
    ):
        got = e.execute("i", q)[0]
        got_serial = serial.execute("i", q)[0]
        assert got == got_serial
        assert got.sum == want
    # Range query through the windowed BSI descent.
    qr = 'Range(frame="f", v > 500)'
    got_cols = e.execute("i", qr)[0].columns().tolist()
    want_cols = serial.execute("i", qr)[0].columns().tolist()
    assert got_cols == want_cols and len(got_cols) > 0


def test_topn_windowed(env):
    holder, idx, e, serial = env
    frame = idx.frame("general")
    base = SLICE_WIDTH - 2048
    for s in range(3):
        off = s * SLICE_WIDTH + base
        frame.import_bits(
            [5] * 30 + [6] * 20 + [7] * 10,
            [off + i for i in range(30)]
            + [off + i for i in range(20)]
            + [off + i for i in range(10)])
    q = ('TopN(Bitmap(frame="general", rowID=5), '
         'frame="general", n=2)')
    assert e.execute("i", q)[0] == serial.execute("i", q)[0]


def test_writes_invalidate_windowed_stacks(env):
    """A write that GROWS the window must invalidate cached narrow
    stacks (version tokens) — stale-width reuse would drop bits."""
    holder, idx, e, serial = env
    frame = idx.frame("general")
    _fill_cluster(frame, [1, 2], n_slices=2, col_lo=0, col_hi=100)
    q = ('Count(Union(Bitmap(frame="general", rowID=1), '
         'Bitmap(frame="general", rowID=2)))')
    assert e.execute("i", q)[0] == 2 * 100
    # Write far outside the current window.
    e.execute("i", f'SetBit(frame="general", rowID=1, '
                   f'columnID={SLICE_WIDTH - 1})')
    assert e.execute("i", q)[0] == 2 * 100 + 1
    assert e.execute("i", q)[0] == serial.execute("i", q)[0]


def test_wider_width_buckets_warm_in_background(env, monkeypatch):
    """After a count at a narrow window, the SAME tree shape's wider
    width buckets compile off the serving path (daemon thread, dummy
    zero stacks) so a write that widens the window never pays a
    serving-path XLA compile. Forced on here (it gates to accelerator
    backends by default)."""
    monkeypatch.setenv("PILOSA_TPU_WARM_WIDTHS", "1")
    holder, idx, e, serial = env
    e._warm_enabled_memo = None  # re-read env
    frame = idx.frame("general")
    _fill_cluster(frame, [1, 2], n_slices=4, col_lo=0, col_hi=120)

    q = ('Count(Intersect(Bitmap(frame="general", rowID=1), '
         'Bitmap(frame="general", rowID=2)))')
    assert e.execute("i", q)[0] == 4 * 120
    t = e._warm_thread
    assert t is not None
    t.join(timeout=120)
    assert not t.is_alive()
    assert e._warm_stats["compiled"] >= 1 and not e._warm_stats["failed"]
    with e._cache_mu:
        widths = sorted({k[-1] for k in e._batched_cache
                         if isinstance(k, tuple) and len(k) == 3})
    from pilosa_tpu import WORDS_PER_SLICE
    assert WORDS_PER_SLICE in widths and len(widths) >= 3, widths

    # Widen the window with a write near the slice top; the count at
    # the new width must be served correctly (program pre-compiled).
    frame.import_bits([1, 2], [SLICE_WIDTH - 2, SLICE_WIDTH - 2])
    assert e.execute("i", q)[0] == 4 * 120 + 1
    assert e.execute("i", q)[0] == serial.execute("i", q)[0]


def test_lazy_window_is_span_exact_not_container_bound(tmp_path):
    """An EVICTED fragment's win32() must bound the data's true word
    span, not its containers: the header alone pins each key to a
    whole 1,024-word container, which for clustered data over-covered
    by 16x and inflated every 10k-slice device stack and fused kernel
    by the same factor (round-4 northstar profile: 53 ms vs 3 ms per
    10B-column Count). word_span peeks array/run payload bounds and
    scans bitmap containers' own bytes."""
    import numpy as np

    from pilosa_tpu.storage.fragment import Fragment

    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0).open()
    # Clustered rows: bits in cols [0, 4000) — true span 63 w64 = 126
    # w32; container bound would be 1024 w64 = 2048 w32.
    rng = np.random.default_rng(7)
    for rid in (1, 2):
        cols = rng.choice(4000, size=300, replace=False).astype(np.uint64)
        f.import_bits(np.full(300, rid, dtype=np.uint64), cols)
    f.snapshot()
    assert f.win32() == (0, 128)          # resident: host window
    f.unload()
    assert f.win32() == (0, 128), "lazy window must match resident"
    # Dense row -> bitmap container; span exactness must survive.
    f2 = Fragment(str(tmp_path / "frag2"), "i", "f", "standard", 0).open()
    f2.import_bits(np.full(5000, 1, dtype=np.uint64),
                   np.arange(64_000, 69_000, dtype=np.uint64))
    f2.snapshot()
    res_win = f2.win32()
    f2.unload()
    assert f2.win32() == res_win
    # Ops on an evicted fragment (append without fault-in is not a
    # thing — but replay through the lazy reader is): write beyond the
    # snapshot span, evict, and the lazy window must cover the op bit.
    f.set_bit(1, 500_000)
    f.unload()
    b, w = f.win32()
    assert b <= (500_000 // 32) < b + w
    f.close()
    f2.close()
