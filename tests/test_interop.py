"""Ecosystem-client interop (VERDICT r3 #8): replay the HTTP
conversations real pilosa clients hold against the server.

Two client populations exist in the reference ecosystem
(docs/client-libraries.md):

- curl/JSON clients — the documented getting-started transcript
  (docs/getting-started.md): status, schema, index/frame create with
  options, PQL over JSON, responses shaped {"attrs": {}, "bits": []} /
  [{"id": n, "count": m}].
- go-pilosa / python-pilosa / java-pilosa — protobuf on the wire:
  POST /index/{i}/query with Content-Type/Accept
  application/x-protobuf carrying internal.QueryRequest, node
  discovery via GET /fragment/nodes, bulk loads via POST /import with
  internal.ImportRequest (internal/public.proto). Our wireproto codec
  is golden-byte-proven against the official protobuf runtime
  (tests/test_wireproto_golden.py), so bytes produced here are the
  bytes those clients produce.
"""
import json
import urllib.request

import pytest

from pilosa_tpu.server import wireproto
from pilosa_tpu.server.server import Server


@pytest.fixture
def server(tmp_path):
    s = Server(str(tmp_path / "data"), bind="127.0.0.1:0")
    s.open()
    yield s
    s.close()


def _http(host, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://{host}{path}",
        data=body.encode() if isinstance(body, str) else body,
        method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_getting_started_json_transcript(server):
    """The documented curl conversation, end to end, with the
    documented response shapes (docs/getting-started.md:30-200)."""
    h = server.host
    # curl localhost:10101/status
    st, _, body = _http(h, "GET", "/status")
    assert st == 200
    status = json.loads(body)["status"]
    assert status["Nodes"][0]["State"] == "UP"
    assert status["Nodes"][0]["Host"]
    # curl localhost:10101/schema  (empty server)
    st, _, body = _http(h, "GET", "/schema")
    assert st == 200 and json.loads(body)["indexes"] in (None, [])
    # curl localhost:10101/index/repository -X POST
    st, _, body = _http(h, "POST", "/index/repository", "")
    assert st == 200 and json.loads(body) == {}
    # frame with time quantum option
    st, _, body = _http(h, "POST", "/index/repository/frame/stargazer",
                        '{"options": {"timeQuantum": "YMD"}}')
    assert st == 200 and json.loads(body) == {}
    st, _, body = _http(h, "POST", "/index/repository/frame/language", "")
    assert st == 200 and json.loads(body) == {}

    # Populate stargazer/language rows via documented SetBit PQL.
    for user, repos in ((14, [1, 2, 3]), (19, [2, 3, 5])):
        for repo in repos:
            st, _, body = _http(
                h, "POST", "/index/repository/query",
                f'SetBit(frame="stargazer", rowID={user}, '
                f'columnID={repo})')
            assert st == 200, body
    for lang, repos in ((5, [1, 2, 3, 5]), (1, [2, 5])):
        for repo in repos:
            _http(h, "POST", "/index/repository/query",
                  f'SetBit(frame="language", rowID={lang}, '
                  f'columnID={repo})')

    # Bitmap: {"attrs": {}, "bits": [...]} exactly as documented.
    st, _, body = _http(h, "POST", "/index/repository/query",
                        'Bitmap(frame="stargazer", rowID=14)')
    res = json.loads(body)["results"][0]
    assert res == {"attrs": {}, "bits": [1, 2, 3]}
    # TopN: [{"id": n, "count": m}] ordered by count.
    st, _, body = _http(h, "POST", "/index/repository/query",
                        'TopN(frame="language", n=5)')
    top = json.loads(body)["results"][0]
    assert top == [{"id": 5, "count": 4}, {"id": 1, "count": 2}]
    # Intersect / Union with the documented multi-line PQL layout.
    st, _, body = _http(
        h, "POST", "/index/repository/query",
        'Intersect(\n    Bitmap(frame="stargazer", rowID=14), \n'
        '    Bitmap(frame="stargazer", rowID=19)\n)')
    assert json.loads(body)["results"][0]["bits"] == [2, 3]
    st, _, body = _http(
        h, "POST", "/index/repository/query",
        'Union(\n    Bitmap(frame="stargazer", rowID=14),\n'
        '    Bitmap(frame="stargazer", rowID=19)\n)')
    assert json.loads(body)["results"][0]["bits"] == [1, 2, 3, 5]
    # SetBit returns {"results":[true]} / repeated write false.
    st, _, body = _http(h, "POST", "/index/repository/query",
                        'SetBit(frame="stargazer", rowID=99, columnID=7)')
    assert json.loads(body)["results"] == [True]
    st, _, body = _http(h, "POST", "/index/repository/query",
                        'SetBit(frame="stargazer", rowID=99, columnID=7)')
    assert json.loads(body)["results"] == [False]
    # Schema now reflects the created tree.
    st, _, body = _http(h, "GET", "/schema")
    idxs = json.loads(body)["indexes"]
    assert idxs[0]["name"] == "repository"
    assert {f["name"] for f in idxs[0]["frames"]} == \
        {"stargazer", "language"}


def test_protobuf_client_conversation(server):
    """The go-pilosa / python-pilosa wire path: node discovery, bulk
    protobuf import, protobuf queries, attrs in protobuf responses
    (internal/public.proto; client.go:923-1011 shapes)."""
    h = server.host
    PB = "application/x-protobuf"
    _http(h, "POST", "/index/repository", "")
    _http(h, "POST", "/index/repository/frame/stargazer", "")

    # Node discovery, as clients route imports: GET /fragment/nodes.
    st, _, body = _http(h, "GET", "/fragment/nodes?index=repository&slice=0")
    assert st == 200
    nodes = json.loads(body)
    assert any(n["host"] == h for n in nodes)

    # Bulk import: internal.ImportRequest protobuf to POST /import.
    rows = [14, 14, 14, 19, 19]
    cols = [1, 2, 3, 2, 3]
    req = wireproto.encode_import_request(
        "repository", "stargazer", 0, rows, cols, [0] * len(rows))
    st, _, body = _http(h, "POST", "/import", req,
                        {"Content-Type": PB, "Accept": PB})
    assert st == 200, body

    # Protobuf query round trip: request AND response protobuf.
    q = wireproto.encode_query_request(
        'Bitmap(frame="stargazer", rowID=14)')
    st, hdrs, body = _http(h, "POST", "/index/repository/query", q,
                           {"Content-Type": PB, "Accept": PB})
    assert st == 200 and "protobuf" in hdrs.get("Content-Type", "")
    resp = wireproto.decode_query_response(body)
    assert not resp.get("error")
    assert resp["results"][0]["bits"] == [1, 2, 3]

    # Row attrs set via PQL, then returned inside the protobuf
    # Bitmap result (attrs ride the wire as typed Attr records).
    _http(h, "POST", "/index/repository/query",
          'SetRowAttrs(frame="stargazer", rowID=14, name="alice", '
          'active=true)')
    st, _, body = _http(h, "POST", "/index/repository/query", q,
                        {"Content-Type": PB, "Accept": PB})
    resp = wireproto.decode_query_response(body)
    assert resp["results"][0]["attrs"] == {"name": "alice",
                                           "active": True}

    # Count + TopN through the same protobuf channel.
    st, _, body = _http(
        h, "POST", "/index/repository/query",
        wireproto.encode_query_request(
            'Count(Bitmap(frame="stargazer", rowID=14))'),
        {"Content-Type": PB, "Accept": PB})
    resp = wireproto.decode_query_response(body)
    assert resp["results"][0] == 3
    st, _, body = _http(
        h, "POST", "/index/repository/query",
        wireproto.encode_query_request('TopN(frame="stargazer", n=2)'),
        {"Content-Type": PB, "Accept": PB})
    resp = wireproto.decode_query_response(body)
    pairs = resp["results"][0]
    assert pairs[0] in ({"id": 14, "count": 3}, (14, 3))

    # Malformed protobuf body: clients expect an error response, not a
    # hang or a 500 traceback.
    st, _, body = _http(h, "POST", "/index/repository/query",
                        b"\xff\xff\xff\xff",
                        {"Content-Type": PB, "Accept": PB})
    assert st == 400
    # Wire-type mismatch (field 1 as varint, not length-delimited)
    # must 400 the same way, not 500 with a traceback.
    st, _, body = _http(h, "POST", "/index/repository/query",
                        b"\x08\x01", {"Content-Type": PB, "Accept": PB})
    assert st == 400, body
