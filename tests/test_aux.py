"""Auxiliary subsystem tests: iterators, URI, membership/failure
detection, diagnostics, stats clients."""
import json
import socket
import urllib.request

import pytest

from pilosa_tpu.diagnostics import Diagnostics
from pilosa_tpu.iterator import (
    EOF,
    BufIterator,
    FragmentIterator,
    LimitIterator,
    SliceIterator,
)
from pilosa_tpu.stats import (
    ExpvarStatsClient,
    MultiStatsClient,
    NopStatsClient,
    new_stats_client,
)
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.utils.uri import URI


# ----------------------------- iterators -----------------------------------

def test_slice_iterator_sorts():
    it = SliceIterator([2, 1, 1], [5, 9, 3])
    assert list(it) == [(1, 3), (1, 9), (2, 5)]


def test_fragment_iterator(tmp_path):
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    f.import_bits([0, 0, 3], [5, 70, 2])
    it = FragmentIterator(f)
    assert list(it) == [(0, 5), (0, 70), (3, 2)]
    it = FragmentIterator(f)
    it.seek(3)
    assert it.next() == (3, 2)
    assert it.next() is EOF
    f.close()


def test_limit_and_buf_iterator():
    base = SliceIterator([0, 1, 250], [1, 2, 3])
    limited = LimitIterator(base, max_row_id=100)
    buf = BufIterator(limited)
    assert buf.peek() == (0, 1)
    assert buf.next() == (0, 1)
    pair = buf.next()
    assert pair == (1, 2)
    buf.unread(pair)
    assert buf.next() == (1, 2)
    assert buf.next() is EOF  # row 250 over the limit


# ------------------------------- uri ---------------------------------------

def test_uri_parse():
    assert URI.parse("localhost:10101").normalize() == "http://localhost:10101"
    assert URI.parse("https://node1:9999").scheme == "https"
    assert URI.parse("node0").host_port() == "node0:10101"
    u = URI.parse("http://10.0.0.1:8080")
    assert (u.host, u.port) == ("10.0.0.1", 8080)
    with pytest.raises(ValueError):
        URI.parse("http://bad host name")


# ---------------------------- membership -----------------------------------

def test_http_nodeset_failure_detection(tmp_path):
    from pilosa_tpu.server.server import Server

    socks = [socket.socket() for _ in range(2)]
    for s in socks:
        s.bind(("localhost", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    hosts = [f"localhost:{p}" for p in ports]

    a = Server(str(tmp_path / "a"), bind=hosts[0], cluster_hosts=hosts,
               replica_n=2, anti_entropy_interval=0, polling_interval=0).open()
    b = Server(str(tmp_path / "b"), bind=hosts[1], cluster_hosts=hosts,
               replica_n=2, anti_entropy_interval=0, polling_interval=0).open()
    try:
        ns = a.cluster.node_set
        ns.suspect_after = 1
        ns.probe_once()
        assert not ns.is_down(b.host)
        assert a.cluster.node_states()[b.host] == "UP"

        b.close()
        ns.probe_once()
        assert ns.is_down(b.host)
        assert a.cluster.node_states()[b.host] == "DOWN"
        assert [n.host for n in ns.nodes()] == [a.host]

        # Queries on A still work (failover excludes the dead node).
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
            timeout=10)
        req = urllib.request.Request(
            f"http://{a.host}/index/i/query",
            data=b'SetBit(frame="f", rowID=1, columnID=2)', method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["results"] == [True]
        req = urllib.request.Request(
            f"http://{a.host}/index/i/query",
            data=b'Count(Bitmap(frame="f", rowID=1))', method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["results"] == [1]

        # A write BURST while B is down: the fan-out hints B's copies
        # per call; replay later batches them into few queries.
        burst = "\n".join(
            f'SetBit(frame="f", rowID=2, columnID={c})'
            for c in range(40))
        req = urllib.request.Request(
            f"http://{a.host}/index/i/query",
            data=burst.encode(), method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert sum(json.loads(resp.read())["results"]) == 40
        assert sum(len(v) for v in a.executor._hints.values()) >= 40

        # Rejoin: restart B on the same port; probe marks it UP, pushes
        # schema (with options) and replays the hinted write.
        b2 = Server(str(tmp_path / "b2"), bind=hosts[1], cluster_hosts=hosts,
                    replica_n=2, anti_entropy_interval=0,
                    polling_interval=0).open()
        try:
            ns.probe_once()
            assert not ns.is_down(b2.host)
            for pql, expect in ((b'Count(Bitmap(frame="f", rowID=1))', 1),
                                (b'Count(Bitmap(frame="f", rowID=2))', 40)):
                req = urllib.request.Request(
                    f"http://{b2.host}/index/i/query",
                    data=pql, method="POST")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    assert json.loads(resp.read())["results"] == [expect]
            assert not a.executor._hints.get(b2.host)
        finally:
            b2.close()
    finally:
        a.close()


# ---------------------------- diagnostics ----------------------------------

def test_diagnostics_opt_in(tmp_path):
    d = Diagnostics(sink_path=None)
    assert d.flush() is None  # disabled by default

    sink = tmp_path / "diag.jsonl"
    d = Diagnostics(sink_path=str(sink))
    rec = d.flush()
    assert rec["OS"] and rec["Version"]
    lines = sink.read_text().strip().splitlines()
    assert len(lines) == 1
    assert json.loads(lines[0])["PythonVersion"] == rec["PythonVersion"]


# ------------------------------- stats -------------------------------------

def test_stats_clients():
    e = new_stats_client("expvar")
    assert isinstance(e, ExpvarStatsClient)
    e.count("queries", 2)
    e.count("queries", 3)
    e.gauge("rows", 7)
    tagged = e.with_tags("index:i")
    tagged.count("queries", 1)
    snap = e.snapshot()
    assert snap["queries"] == 5
    assert snap["rows"] == 7
    assert snap["queries;index:i"] == 1

    m = MultiStatsClient([ExpvarStatsClient(), NopStatsClient()])
    m.count("x")
    m.timing("t", 0.5)

    with pytest.raises(ValueError):
        new_stats_client("bogus")


def test_translate_store(tmp_path):
    """Key→ID translation: dense allocation, idempotence, persistence
    (pilosa_tpu/storage/translate.py)."""
    from pilosa_tpu.storage.translate import TranslateStore

    path = str(tmp_path / "keys.db")
    ts = TranslateStore(path).open()
    assert ts.translate(["a", "b", "a", "c"]) == [0, 1, 0, 2]
    assert ts.translate(["c", "d"]) == [2, 3]
    assert ts.key_of(1) == "b"
    assert ts.key_of(99) is None
    ts.close()
    # reopen: allocations survive and continue densely
    ts2 = TranslateStore(path).open()
    assert ts2.translate(["b", "e"]) == [1, 4]
    ts2.close()


def test_public_testing_harness():
    """pilosa_tpu.testing — the reference's importable test/ package
    analog (SURVEY layer X3): TestHolder.reopen, TestFragment,
    ServerCluster, deterministic hashers."""
    import json
    import urllib.request

    from pilosa_tpu.testing import (
        ModHasher,
        ServerCluster,
        TestFragment,
        TestHolder,
        must_parse,
        new_test_cluster,
    )

    with TestHolder() as h:
        idx = h.create_index("i")
        idx.create_frame("f").set_bit("standard", 1, 2)
        h.reopen()
        assert h.fragment("i", "f", "standard", 0).row_count(1) == 1

    with TestFragment() as f:
        f.set_bit(3, 4)
        f.reopen()
        assert f.row_count(3) == 1

    c = new_test_cluster(3)
    assert isinstance(c.hasher, ModHasher)
    # deterministic: slice -> node is predictable under ModHasher
    assert c.fragment_nodes("i", 0) == c.fragment_nodes("i", 0)

    assert must_parse('Count(Bitmap(rowID=1))').calls[0].name == "Count"

    with ServerCluster(2, replica_n=2) as servers:
        b = f"http://{servers[0].host}"
        req = urllib.request.Request(f"{b}/index/i", data=b"{}",
                                     method="POST")
        urllib.request.urlopen(req, timeout=10)
        req = urllib.request.Request(f"{b}/index/i/frame/f", data=b"{}",
                                     method="POST")
        urllib.request.urlopen(req, timeout=10)
        req = urllib.request.Request(
            f"{b}/index/i/query",
            data=b'SetBit(frame="f", rowID=1, columnID=2)', method="POST")
        urllib.request.urlopen(req, timeout=10)
        # replicated to the second node
        req = urllib.request.Request(
            f"http://{servers[1].host}/index/i/query",
            data=b'Count(Bitmap(frame="f", rowID=1))', method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["results"] == [1]


def test_prometheus_metrics_endpoint(tmp_path):
    """GET /metrics renders the expvar snapshot as Prometheus text
    exposition: tagged counters become labeled series, governor
    gauges appear namespaced, non-numeric values are skipped."""
    import json
    import urllib.request

    from pilosa_tpu.server.server import Server

    server = Server(str(tmp_path / "d"), bind="127.0.0.1:0")
    server.open()
    try:
        def post(path, body):
            req = urllib.request.Request(
                f"http://{server.host}{path}", data=body.encode(),
                method="POST")
            return json.loads(
                urllib.request.urlopen(req, timeout=10).read() or b"{}")

        post("/index/i", "{}")
        post("/index/i/frame/f", "{}")
        post("/index/i/query", 'SetBit(frame="f", rowID=1, columnID=2)')

        with urllib.request.urlopen(
                f"http://{server.host}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        lines = [ln for ln in text.splitlines() if ln]
        assert lines, "empty exposition"
        # Family blocks lead with exactly one '# TYPE' line; every
        # sample line is 'name{labels} value' or 'name value' with a
        # numeric value and the pilosa_ namespace.
        assert any(ln.startswith("# TYPE pilosa_") for ln in lines)
        for ln in lines:
            if ln.startswith("#"):
                continue
            assert ln.startswith("pilosa_"), ln
            float(ln.rsplit(" ", 1)[1])
        # The SetBit counter carries its index tag as a label (the
        # executor counts calls at index scope, executor.py).
        setbit = [ln for ln in lines if ln.startswith("pilosa_SetBit")]
        assert setbit and 'index="i"' in setbit[0], setbit
    finally:
        server.close()


def test_prometheus_exposition_escaping():
    from pilosa_tpu.stats import prometheus_exposition

    out = prometheus_exposition({
        'Weird Name!;tag:va"l\\ue': 3,
        "plain": 1.5,
        "skipped": "not-a-number",
        "flag": True,  # bools are not samples
    }, namespaced=[("grp", {"a": 2, "b": "nope"})])
    assert 'pilosa_Weird_Name_{tag="va\\"l\\\\ue"} 3' in out
    assert "pilosa_plain 1.5" in out
    assert "skipped" not in out and "flag" not in out
    assert "pilosa_grp_a 2" in out and "b" not in out.split()


def test_fast_http_parse_protocol_edges(tmp_path):
    """The fast header parser must keep the stdlib's protocol
    guarantees: 100-continue answered, whitespace-before-colon and
    conflicting Content-Length rejected (request-smuggling
    differentials), duplicates first-wins, lowercase headers honored,
    folding tolerated."""
    import socket

    from pilosa_tpu.server.server import Server

    server = Server(str(tmp_path / "d"), bind="127.0.0.1:0")
    server.open()
    host, port = server.host.rsplit(":", 1)

    def raw(req):
        s = socket.create_connection((host, int(port)), timeout=10)
        s.sendall(req)
        s.settimeout(10)
        out = b""
        try:
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                out += chunk
                if b"\r\n\r\n" in out and b"HTTP/1.1 100" not in \
                        out.rsplit(b"\r\n\r\n", 1)[0]:
                    break
        except socket.timeout:
            pass
        s.close()
        return out

    try:
        # Expect: 100-continue gets the interim response, then 200.
        body = b'{}'
        out = raw(b"POST /index/i HTTP/1.1\r\nHost: x\r\n"
                  b"Expect: 100-continue\r\n"
                  b"Content-Length: " + str(len(body)).encode()
                  + b"\r\nConnection: close\r\n\r\n" + body)
        assert b"100 Continue" in out, out[:120]
        assert b"200" in out.split(b"\r\n", 1)[0] or b"HTTP/1.1 200" in out

        # Whitespace before the colon: rejected.
        out = raw(b"GET /version HTTP/1.1\r\nHost : x\r\n"
                  b"Connection: close\r\n\r\n")
        assert b"400" in out.split(b"\r\n", 1)[0], out[:120]

        # Conflicting Content-Length: rejected.
        out = raw(b"POST /index/i/query HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: 10\r\nContent-Length: 0\r\n"
                  b"Connection: close\r\n\r\n" + b"x" * 10)
        assert b"400" in out.split(b"\r\n", 1)[0], out[:120]

        # Identical duplicate Content-Length: tolerated, first wins.
        out = raw(b"GET /version HTTP/1.1\r\nHost: x\r\n"
                  b"Accept: application/json\r\nAccept: text/html\r\n"
                  b"Connection: close\r\n\r\n")
        assert out.split(b"\r\n", 1)[0].endswith(b"200 OK"), out[:120]

        # Lowercase header names reach handlers canonically.
        out = raw(b"POST /index/i/query HTTP/1.1\r\nhost: x\r\n"
                  b"content-length: 36\r\nconnection: close\r\n\r\n"
                  b'SetBit(frame="f", rowID=1, columnID=')
        # Body is junk PQL -> 400 from the HANDLER (not a hang: the
        # lowercase content-length was honored and the body consumed).
        assert b"400" in out.split(b"\r\n", 1)[0], out[:120]
    finally:
        server.close()


def test_http_parser_raw_fuzz(tmp_path):
    """Random garbage, truncated requests, and oversized headers at
    the socket level: every connection must end in a response or a
    clean close — and the server must still serve real requests
    afterwards (no wedged handler threads, no tracebacks that kill
    the acceptor)."""
    import random
    import socket

    from pilosa_tpu.server.server import Server

    server = Server(str(tmp_path / "d"), bind="127.0.0.1:0")
    server.open()
    host, port = server.host.rsplit(":", 1)
    rng = random.Random(0xF00D)
    try:
        cases = []
        for _ in range(20):
            n = rng.randrange(1, 400)
            cases.append(bytes(rng.randrange(256) for _ in range(n)))
        cases += [
            b"GET",                        # truncated request line
            b"GET / HTTP/9.9\r\n\r\n",     # bad version
            b"GET / HTTP/1.1\r\n" + b"X: y\r\n" * 250 + b"\r\n",
            b"GET / HTTP/1.1\r\nA" + b"a" * 70000 + b": v\r\n\r\n",
            b"POST /index/i/query HTTP/1.1\r\nContent-Length: zzz"
            b"\r\n\r\n",
            b"\r\n\r\n\r\n",
            b"GET / HTTP/1.1\r\n: novalue\r\n\r\n",
            b"GET / HTTP/1.1\r\n\tfold-without-anchor\r\n\r\n",
        ]
        for raw in cases:
            s = socket.create_connection((host, int(port)), timeout=5)
            try:
                s.sendall(raw)
                s.settimeout(1)
                try:
                    while s.recv(65536):
                        pass
                except socket.timeout:
                    pass
            except OSError:
                pass  # reset mid-send: fine, that's a rejection
            finally:
                s.close()
        # Server still fully serves after the abuse.
        import urllib.request

        with urllib.request.urlopen(
                f"http://{server.host}/version", timeout=10) as r:
            assert r.status == 200
    finally:
        server.close()
