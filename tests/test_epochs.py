"""Distributed mutation epochs (PR 5): the epoch-vector registry, the
persistent fan-out pool, parallel replica posts, and the 2-node
acceptance criteria — read-your-writes through a relaying coordinator,
remote-write memo invalidation within the probe TTL, and the
``client.epoch.stale`` failpoint degrading caches to cold, never stale.

The acceptance tests boot REAL subprocess servers: in-process
``ServerCluster`` nodes share the module-global epoch counters in
storage/fragment.py, which would let a "remote-only" write invalidate
the local node's caches through the shared process state instead of
through the wire protocol under test.
"""
import http.client
import json
import os
import subprocess
import sys
import time

import pytest

from pilosa_tpu.cluster.epochs import (
    ClusterEpochs,
    EPOCH_HEADER,
    decode_epochs,
    encode_epochs,
)


# ------------------------------------------------------------- wire codec


def test_epoch_header_roundtrip():
    eps = {"idx-a": 3, "weird name;x=1,y": 7, "*": 12}
    host, out = decode_epochs(encode_epochs("node-1:10101", eps))
    assert host == "node-1:10101"
    assert out == eps


def test_epoch_header_garbage_rejected():
    with pytest.raises(ValueError):
        decode_epochs(";i=1")
    with pytest.raises(ValueError):
        decode_epochs("host;i")
    with pytest.raises(ValueError):
        decode_epochs("host;i=xyz")


# --------------------------------------------------------------- registry


class _StubHolder:
    def __init__(self, *names):
        self.indexes = {n: None for n in names}


def _tok_counters(tok):
    """{host: counter} from a (host, incarnation, counter) token."""
    return {h: ctr for h, _inc, ctr in tok}


def test_registry_token_cold_until_observed_and_ttl_expires():
    from pilosa_tpu.storage import fragment as frag

    reg = ClusterEpochs("a:1", _StubHolder("i"), ttl=0.05)
    hosts = ["a:1", "b:2"]
    # Unknown peer -> cold (None), never a guess.
    assert reg.token("i", hosts) is None
    reg.observe("b:2", {"i": 4, "*": 9})
    tok = reg.token("i", hosts)
    assert tok is not None
    assert _tok_counters(tok)["b:2"] == 4
    assert _tok_counters(tok)["a:1"] == frag.mutation_epoch("i")
    # An index the peer never listed falls back to its * total.
    reg2 = ClusterEpochs("a:1", _StubHolder("other"), ttl=0.05)
    reg2.observe("b:2", {"i": 4, "*": 9})
    tok2 = reg2.token("other", hosts)
    assert _tok_counters(tok2)["b:2"] == 9
    # TTL expiry -> cold again (stale is never served).
    time.sleep(0.06)
    assert reg.token("i", hosts) is None
    # A changed observation mints a new version (worker publication).
    v0 = reg._version
    reg.observe("b:2", {"i": 5, "*": 10})
    assert reg._version == v0 + 1
    # Local-only host set never goes cold.
    assert reg.token("i", ["a:1"]) is not None


def test_registry_local_write_changes_token():
    from pilosa_tpu.storage import fragment as frag

    reg = ClusterEpochs("a:1", _StubHolder("tok_idx"), ttl=5)
    reg.observe("b:2", {"tok_idx": 1, "*": 1})
    t1 = reg.token("tok_idx", ["a:1", "b:2"])
    frag._bump_epoch("tok_idx")
    t2 = reg.token("tok_idx", ["a:1", "b:2"])
    assert t1 is not None and t2 is not None and t1 != t2


def test_registry_peer_restart_never_revalidates():
    """A restarted peer's counters reset and may climb back to a
    stored token's values — the boot-incarnation nonce in the token
    keeps the old token from ever re-validating."""
    reg = ClusterEpochs("a:1", _StubHolder("i"), ttl=5)
    reg.observe("b:2", {"i": 5, "*": 5, "!": 111})
    t1 = reg.token("i", ["a:1", "b:2"])
    reg.observe("b:2", {"i": 5, "*": 5, "!": 222})  # same counters!
    t2 = reg.token("i", ["a:1", "b:2"])
    assert t1 is not None and t2 is not None and t1 != t2


def test_registry_stale_failpoint_drops_observations():
    from pilosa_tpu import faults

    faults.enable("client.epoch.stale=corrupt")
    try:
        reg = ClusterEpochs("a:1", _StubHolder("i"), ttl=5)
        reg.observe("b:2", {"i": 4, "*": 9})
        assert reg.token("i", ["a:1", "b:2"]) is None  # cold
        assert reg.counters["observations"] == 0
    finally:
        faults.disable()


def test_registry_header_memoized_on_epoch_total():
    from pilosa_tpu.storage import fragment as frag

    reg = ClusterEpochs("a:1", _StubHolder("hdr_idx"), ttl=5)
    v1 = reg.header_value()
    assert reg.header_value() is v1  # memo hit: same object
    frag._bump_epoch("hdr_idx")
    v2 = reg.header_value()
    assert v2 is not v1
    host, eps = decode_epochs(v2)
    assert host == "a:1"
    assert eps["hdr_idx"] == frag.mutation_epoch("hdr_idx")


# ---------------------------------------------------------- fan-out pool


def test_fanout_pool_reuses_threads_and_never_blocks():
    import threading

    from pilosa_tpu.utils.fanpool import FanoutPool

    pool = FanoutPool(max_idle=2)
    try:
        # Sequential tasks reuse the same parked worker: no spillover,
        # at most one persistent thread minted.
        seen = []
        for i in range(20):
            pool.run(lambda i=i: seen.append(i)).wait()
        assert seen == list(range(20))
        st = pool.stats()
        assert st["persistent"] <= 2 and st["spilled"] == 0

        # A burst beyond max_idle spills to one-shot threads instead
        # of queuing (queueing would deadlock nested fan-outs).
        gate = threading.Event()
        waits = [pool.run(gate.wait) for _ in range(6)]
        gate.set()
        for w in waits:
            assert w.wait(5)
        assert pool.stats()["spilled"] >= 4

        # A raising task still completes its handle.
        def boom():
            raise RuntimeError("x")

        assert pool.run(boom).wait(5)
    finally:
        pool.close()


def test_fanout_pool_nested_runs_do_not_deadlock():
    from pilosa_tpu.utils.fanpool import FanoutPool

    pool = FanoutPool(max_idle=1)
    try:
        out = []

        def outer():
            inner_waits = [pool.run(lambda i=i: out.append(i))
                           for i in range(4)]
            for w in inner_waits:
                w.wait()
            out.append("outer")

        assert pool.run(outer).wait(10)
        assert sorted(out, key=str) == [0, 1, 2, 3, "outer"]
    finally:
        pool.close()


# ------------------------------------------------- parallel replica posts


def test_import_bits_posts_all_owners_and_fails_on_any():
    """ReplicaN>=2 import posts run concurrently; the error contract
    (any owner failure fails the import) survives."""
    from pilosa_tpu.cluster.client import ClientError, InternalClient

    class Node:
        def __init__(self, host):
            self.host = host

        def uri(self):
            return f"http://{self.host}"

    class FakeCluster:
        def fragment_nodes(self, index, slice_num):
            return [Node("good-1:1"), Node("bad:2"), Node("good-2:3")]

    client = InternalClient()
    posted = []

    def fake_do(method, url, body=None, **kw):
        posted.append(url)
        if "bad" in url:
            return 500, b'{"error": "boom"}', {}
        return 200, b"{}", {}

    client._do = fake_do
    with pytest.raises(ClientError):
        client.import_bits(FakeCluster(), "i", "f", 0, [1], [2])
    assert len(posted) == 3  # every owner attempted, in parallel
    posted.clear()
    # All-good path: no error, all owners hit.
    client._do = lambda m, u, body=None, **kw: (
        posted.append(u), (200, b"{}", {}))[1]
    client.import_bits(FakeCluster(), "i", "f", 0, [1], [2])
    assert len(posted) == 3
    client.close()


# ------------------------------------------------- subprocess 2-node rig


def _http(host, method, path, body=None, timeout=30):
    h, _, p = host.rpartition(":")
    conn = http.client.HTTPConnection(h, int(p), timeout=timeout)
    try:
        conn.request(method, path,
                     body=body.encode() if isinstance(body, str) else body)
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _wait_ready(host, timeout=90):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            st, _, _ = _http(host, "GET", "/version", timeout=5)
            if st == 200:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise RuntimeError(f"node {host} never became ready")


def _spawn_cluster(tmp_path, hosts, env_per_node=None, ttl="0.3"):
    procs = []
    for i, host in enumerate(hosts):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PILOSA_EPOCH_PROBE_TTL"] = ttl
        env.update((env_per_node or {}).get(i, {}))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", str(tmp_path / f"n{i}"), "-b", host,
             "--cluster-hosts", ",".join(hosts)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
    try:
        for host in hosts:
            _wait_ready(host)
    except BaseException:
        for p in procs:
            p.kill()
        raise
    return procs


def _kill_cluster(procs):
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()


def _owned_columns(hosts, index):
    """One column per node, owned by that node under replica_n=1 —
    computed with the servers' own placement math."""
    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.cluster.cluster import Cluster, Node

    cluster = Cluster(nodes=[Node(h) for h in hosts], replica_n=1)
    cols = {}
    for s in range(64):
        owner = cluster.fragment_nodes(index, s)[0].host
        if owner not in cols:
            cols[owner] = s * SLICE_WIDTH + 1
        if len(cols) == len(hosts):
            return cols
    raise RuntimeError("placement never covered every node")


@pytest.mark.slow
def test_2node_read_your_writes_and_replay(tmp_path):
    """Acceptance: write through node A (relayed to owner B), an
    identical query through A replays only post-write results; through
    B it must miss or re-validate (never return pre-write bytes)."""
    from pilosa_tpu.testing import free_ports

    hosts = [f"127.0.0.1:{p}" for p in free_ports(2)]
    a, b = hosts
    cols = _owned_columns(hosts, "i")
    procs = _spawn_cluster(tmp_path, hosts)
    try:
        assert _http(a, "POST", "/index/i", "{}")[0] == 200
        assert _http(a, "POST", "/index/i/frame/f", "{}")[0] == 200
        # Seed one bit owned by each node, written through A.
        for host in hosts:
            st, _, body = _http(
                a, "POST", "/index/i/query",
                f'SetBit(frame="f", rowID=1, columnID={cols[host]})')
            assert st == 200, body

        q = 'Count(Bitmap(frame="f", rowID=1))'
        st, h1, b1 = _http(a, "POST", "/index/i/query", q)
        assert st == 200 and json.loads(b1)["results"] == [2]
        # Epoch piggyback present on every multi-node response.
        assert EPOCH_HEADER in h1
        st, h2, b2 = _http(a, "POST", "/index/i/query", q)
        assert st == 200 and b2 == b1
        assert h2.get("X-Pilosa-Response-Cache") == "hit"

        # Write through A to a B-owned column: A relays to B, B's ack
        # piggybacks its bumped counter — the very next identical
        # query through A must NOT replay the pre-write bytes.
        st, _, body = _http(
            a, "POST", "/index/i/query",
            f'SetBit(frame="f", rowID=1, columnID={cols[b] + 7})')
        assert st == 200, body
        st, h3, b3 = _http(a, "POST", "/index/i/query", q)
        assert st == 200 and json.loads(b3)["results"] == [3]
        assert h3.get("X-Pilosa-Response-Cache") != "hit"
        # And the post-write answer becomes the new warm entry.
        st, h4, b4 = _http(a, "POST", "/index/i/query", q)
        assert st == 200 and json.loads(b4)["results"] == [3]
        assert h4.get("X-Pilosa-Response-Cache") == "hit"

        # Through the OTHER coordinator: never the pre-write value.
        st, h5, b5 = _http(b, "POST", "/index/i/query", q)
        assert st == 200 and json.loads(b5)["results"] == [3]

        # /debug/epochs shows the peer vector on both nodes.
        st, _, body = _http(a, "GET", "/debug/epochs")
        snap = json.loads(body)
        assert snap["enabled"] and b in snap["peers"]
    finally:
        _kill_cluster(procs)


@pytest.mark.slow
def test_2node_remote_write_invalidates_within_probe_ttl(tmp_path):
    """Acceptance: a remote-ONLY write (through B, to a B-owned slice
    — A never sees it) invalidates A's executor memos and response
    replay within the probe TTL."""
    from pilosa_tpu.testing import free_ports

    ttl = 0.3
    hosts = [f"127.0.0.1:{p}" for p in free_ports(2)]
    a, b = hosts
    cols = _owned_columns(hosts, "i")
    procs = _spawn_cluster(tmp_path, hosts, ttl=str(ttl))
    try:
        assert _http(a, "POST", "/index/i", "{}")[0] == 200
        assert _http(a, "POST", "/index/i/frame/f", "{}")[0] == 200
        for host in hosts:
            _http(a, "POST", "/index/i/query",
                  f'SetBit(frame="f", rowID=1, columnID={cols[host]})')
        q = 'Count(Bitmap(frame="f", rowID=1))'
        st, _, b1 = _http(a, "POST", "/index/i/query", q)
        assert json.loads(b1)["results"] == [2]
        st, h2, _ = _http(a, "POST", "/index/i/query", q)
        assert h2.get("X-Pilosa-Response-Cache") == "hit"

        # Remote-only write: straight to B, landing on B's own slice.
        st, _, body = _http(
            b, "POST", "/index/i/query",
            f'SetBit(frame="f", rowID=1, columnID={cols[b] + 7})')
        assert st == 200, body

        # Within <= TTL (+ margin), A's warm tiers must converge to
        # the post-write answer — and once converged, never regress.
        deadline = time.monotonic() + ttl * 10 + 5
        converged_at = None
        while time.monotonic() < deadline:
            st, _, body = _http(a, "POST", "/index/i/query", q)
            val = json.loads(body)["results"][0]
            if val == 3:
                converged_at = time.monotonic()
                break
            assert val == 2  # pre-write value, inside the bound
            time.sleep(0.05)
        assert converged_at is not None, "A never saw B's write"
        for _ in range(3):
            st, _, body = _http(a, "POST", "/index/i/query", q)
            assert json.loads(body)["results"] == [3]
    finally:
        _kill_cluster(procs)


@pytest.mark.slow
@pytest.mark.faults
def test_2node_epoch_stale_failpoint_cold_never_stale(tmp_path):
    """Satellite: with ``client.epoch.stale`` armed on A (dropped
    epoch propagation — a partition of the epoch plane), A's caches
    degrade to COLD: every read takes the full fan-out (correct,
    reflecting B's writes immediately) and no replay is ever served."""
    from pilosa_tpu.testing import free_ports

    hosts = [f"127.0.0.1:{p}" for p in free_ports(2)]
    a, b = hosts
    cols = _owned_columns(hosts, "i")
    procs = _spawn_cluster(
        tmp_path, hosts,
        env_per_node={0: {"PILOSA_FAULTS": "client.epoch.stale=corrupt"}})
    try:
        assert _http(a, "POST", "/index/i", "{}")[0] == 200
        assert _http(a, "POST", "/index/i/frame/f", "{}")[0] == 200
        for host in hosts:
            _http(a, "POST", "/index/i/query",
                  f'SetBit(frame="f", rowID=1, columnID={cols[host]})')
        q = 'Count(Bitmap(frame="f", rowID=1))'
        count = 2
        for round_num in range(3):
            for _ in range(3):
                st, hdrs, body = _http(a, "POST", "/index/i/query", q)
                assert st == 200
                # Cold: correct, and never a replay.
                assert json.loads(body)["results"] == [count]
                assert hdrs.get("X-Pilosa-Response-Cache") != "hit"
            # B's writes are visible to A IMMEDIATELY (cold = full
            # fan-out), despite zero epoch propagation.
            st, _, body = _http(
                b, "POST", "/index/i/query",
                f'SetBit(frame="f", rowID=1, '
                f'columnID={cols[b] + 11 + round_num})')
            assert st == 200, body
            count += 1
        st, _, body = _http(a, "GET", "/debug/epochs")
        snap = json.loads(body)
        assert snap["enabled"]
        assert snap["counters"]["cold"] > 0
        assert not any(p["fresh"] for p in snap["peers"].values())
        # B (unarmed) replays normally — the failpoint is A-local.
        st, _, _ = _http(b, "POST", "/index/i/query", q)
        st, h2, _ = _http(b, "POST", "/index/i/query", q)
        assert h2.get("X-Pilosa-Response-Cache") == "hit"
    finally:
        _kill_cluster(procs)
