"""Replica-mesh (2-D replica × slice) distribution tests on the
8-device CPU mesh (conftest forces JAX_PLATFORMS=cpu with 8 virtual
devices)."""
import jax
import numpy as np
import pytest

from pilosa_tpu.parallel import distributed as dist


needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs 8 virtual devices")


def _rows(s, w, seed=0):
    return np.random.default_rng(seed).integers(
        0, 1 << 32, size=(s, w), dtype=np.uint64).astype(np.uint32)


@needs8
def test_replica_mesh_shape():
    mesh = dist.make_replica_mesh(replica_n=2)
    assert mesh.shape[dist.REPLICA_AXIS] == 2
    assert mesh.shape[dist.SLICE_AXIS] == 4


def test_replica_n_must_divide():
    with pytest.raises(ValueError):
        dist.make_replica_mesh(replica_n=3, n_devices=8)


@needs8
def test_count_and_matches_numpy_across_replicas():
    mesh = dist.make_replica_mesh(replica_n=2)
    eng = dist.ReplicaMeshEngine(mesh)
    a_h, b_h = _rows(8, 256, 1), _rows(8, 256, 2)
    a, b = eng.shard_rows(a_h), eng.shard_rows(b_h)
    want = int(np.bitwise_count(a_h & b_h).sum())
    assert int(eng.count_and(a, b)) == want


@needs8
def test_topn_counts_matches_numpy():
    mesh = dist.make_replica_mesh(replica_n=2)
    eng = dist.ReplicaMeshEngine(mesh)
    m_h = np.random.default_rng(3).integers(
        0, 1 << 32, size=(4, 6, 256), dtype=np.uint64).astype(np.uint32)
    m = jax.device_put(
        m_h, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(dist.SLICE_AXIS)))
    want = np.bitwise_count(m_h).sum(axis=(0, 2))
    got = np.asarray(eng.topn_counts(m))
    assert (got == want).all()


@needs8
def test_replica_digest_consistent_copies():
    mesh = dist.make_replica_mesh(replica_n=2)
    eng = dist.ReplicaMeshEngine(mesh)
    rows = eng.shard_rows(_rows(8, 256, 4))
    assert eng.replicas_consistent(rows)
    d = np.asarray(eng.replica_digest(rows))
    assert d.shape == (2,)


@needs8
def test_replica_digest_detects_divergence():
    """A corrupted replica copy must produce a different digest.

    Build the array with per-device buffers so one replica's copy
    diverges — the staging path a failed/partially-written replica
    would produce."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = dist.make_replica_mesh(replica_n=2)
    eng = dist.ReplicaMeshEngine(mesh)
    host = _rows(8, 256, 5)
    sharding = NamedSharding(mesh, P(dist.SLICE_AXIS))
    per_dev = 8 // mesh.shape[dist.SLICE_AXIS]

    bufs = []
    for d, idx in sharding.addressable_devices_indices_map((8, 256)).items():
        shard = host[idx].copy()
        if d == mesh.devices[1, 0]:  # corrupt replica row 1's first shard
            shard[0, 0] ^= np.uint32(0xDEADBEEF)
        bufs.append(jax.device_put(shard, d))
    arr = jax.make_array_from_single_device_arrays((8, 256), sharding, bufs)
    assert not eng.replicas_consistent(arr)


@needs8
def test_process_slice_range_single_process_covers_all():
    mesh = dist.make_replica_mesh(replica_n=1)
    lo, hi = dist.process_slice_range(16, mesh)
    assert (lo, hi) == (0, 16)


@needs8
def test_stage_process_local_single_process():
    mesh = dist.make_replica_mesh(replica_n=1)
    host = _rows(8, 256, 6)
    arr = dist.stage_process_local(host, host.shape, mesh)
    assert (np.asarray(arr) == host).all()


def test_init_distributed_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("PILOSA_COORDINATOR", raising=False)
    assert dist.init_distributed() is False


def test_slices_by_node_memo_correctness():
    """The _slices_by_node memo decides slice→node routing: it must
    (a) give identical mappings on hits, (b) invalidate on topology
    change AND on live-node-set change (failover), and (c) never let a
    span-look-alike non-contiguous list ([0, 2, 2] spans like
    [0, 1, 2]) poison the contiguous key."""
    from pilosa_tpu.cluster.cluster import Cluster, Node
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage.holder import Holder
    import tempfile

    cl = Cluster(nodes=[Node(f"h{i}") for i in range(3)], replica_n=2)
    ex = Executor(Holder(tempfile.mkdtemp()))
    ex.cluster = cl
    nodes = list(cl.nodes)
    full = list(range(64))

    m1 = ex._slices_by_node(nodes, "i", full)
    m2 = ex._slices_by_node(nodes, "i", full)
    assert m1 == m2
    assert sorted(s for v in m1.values() for s in v) == full
    # Returned dict is a fresh copy per call: caller-side dict churn
    # can't corrupt the memo.
    m1.pop(next(iter(m1)))
    assert ex._slices_by_node(nodes, "i", full) == m2

    # Failover: a shrunken live-node list must not hit the full-list
    # entry (the dead node's slices must remap).
    dead = nodes[0]
    live = [n for n in nodes if n is not dead]
    m3 = ex._slices_by_node(live, "i", full)
    assert dead not in m3
    assert sorted(s for v in m3.values() for s in v) == full

    # Topology change: a join must invalidate (new node owns slices).
    cl.nodes.append(Node("h3"))
    cl.topology_version += 1
    m4 = ex._slices_by_node(list(cl.nodes), "i", full)
    assert sorted(s for v in m4.values() for s in v) == full
    assert any(n.host == "h3" for n in m4), "joined node owns nothing"

    # Span look-alike ABOVE the memo threshold: same length, first,
    # and last as range(64) but with a duplicate — must neither read
    # nor poison the contiguous entry.
    look = [0] + list(range(2, 64)) + [63]  # dup 63, missing 1
    assert len(look) == 64 and look[0] == 0 and look[-1] == 63
    odd = ex._slices_by_node(list(cl.nodes), "i", look)
    assert sorted(s for v in odd.values() for s in v) == sorted(look)
    cont = ex._slices_by_node(list(cl.nodes), "i", list(range(64)))
    assert sorted(s for v in cont.values() for s in v) == list(range(64))


def _rb_executor(tmp_path):
    import tempfile

    from pilosa_tpu.cluster.cluster import Cluster, Node
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage.holder import Holder

    ex = Executor(Holder(tempfile.mkdtemp(dir=tmp_path)))
    ex.cluster = Cluster(nodes=[Node("a"), Node("b")], replica_n=1)
    ex.host = "a"
    return ex


def test_remote_batcher_fuses_concurrent_subcalls(tmp_path):
    """While one round trip to a peer is in flight, concurrent
    subcalls for the same (index, slices) must go out as ONE
    multi-call query when it returns — and every caller must get ITS
    OWN positional result."""
    import threading
    import time

    from pilosa_tpu.cluster.cluster import Node
    from pilosa_tpu.pql import parse

    ex = _rb_executor(tmp_path)
    node = Node("b")
    sent = []          # (n_calls, call_strs) per wire request
    release = threading.Event()

    class StubClient:
        def execute_query(self, node_, index, query, slices=None,
                          remote=False, **kw):
            sent.append([str(c) for c in query.calls])
            if len(sent) == 1:
                release.wait(timeout=30)  # first flight: let others park
            # Result per call: its rowID (proves positional mapping).
            return [int(str(c).split("rowID=")[1].rstrip(")"))
                    for c in query.calls]

    ex.client = StubClient()
    results = {}

    def issue(row):
        call = parse(f'Count(Bitmap(frame="f", rowID={row}))').calls[0]
        results[row] = ex._remote_execute(node, "i", call, [0, 1])

    threads = [threading.Thread(target=issue, args=(r,))
               for r in (1, 2, 3, 4)]
    threads[0].start()
    time.sleep(0.3)          # leader in flight
    for t in threads[1:]:
        t.start()
    time.sleep(0.3)          # the rest parked on the lane
    release.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()

    assert results == {1: 1, 2: 2, 3: 3, 4: 4}
    assert len(sent[0]) == 1              # leader flew alone
    assert sorted(len(s) for s in sent[1:]) and sum(
        len(s) for s in sent[1:]) == 3    # followers batched
    assert max(len(s) for s in sent) >= 2, sent
    assert ex._rb_stats["batched_calls"] >= 2


def test_remote_batcher_poisoned_batch_retries_singly(tmp_path):
    """One bad call in a batch (unknown frame etc.) must fail ONLY its
    own requester: the batch error triggers single retries."""
    import threading
    import time

    from pilosa_tpu.cluster.cluster import Node
    from pilosa_tpu.cluster.client import ClientError
    from pilosa_tpu.pql import parse

    ex = _rb_executor(tmp_path)
    node = Node("b")
    release = threading.Event()
    calls_log = []

    class StubClient:
        def execute_query(self, node_, index, query, slices=None,
                          remote=False, **kw):
            texts = [str(c) for c in query.calls]
            calls_log.append(texts)
            if len(calls_log) == 1:
                release.wait(timeout=30)
                return [0]
            if any("rowID=666" in t for t in texts):
                raise ClientError("frame not found", status=400)
            return [7 for _ in texts]

    ex.client = StubClient()
    outcomes = {}

    def issue(row):
        call = parse(f'Count(Bitmap(frame="f", rowID={row}))').calls[0]
        try:
            outcomes[row] = ex._remote_execute(node, "i", call, [0])
        except ClientError as e:
            outcomes[row] = f"err:{e}"

    threads = [threading.Thread(target=issue, args=(r,))
               for r in (5, 666, 8)]
    threads[0].start()
    time.sleep(0.3)
    for t in threads[1:]:
        t.start()
    time.sleep(0.3)
    release.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert outcomes[5] == 0          # the lone leader
    assert outcomes[8] == 7          # sibling survived the poison
    assert str(outcomes[666]).startswith("err:"), outcomes
