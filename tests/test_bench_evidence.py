"""bench.py cached-evidence fallback (tools/tpu_watch.py integration).

Round 2's lesson: the TPU relay can be dead at bench time even when it
was healthy earlier in the round. tpu_watch.py captures evidence
opportunistically; bench._cached_evidence must replay it honestly
(capture-time tag, freshness bound) and never replay stale or corrupt
evidence. This is the round's evidence-capture contract, so it gets the
same test treatment as any other subsystem.
"""
import importlib.util
import json
import os
import time

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench_mod():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _iso_age(age_s):
    from datetime import datetime, timedelta, timezone
    t = datetime.now(timezone.utc) - timedelta(seconds=age_s)
    return t.strftime("%Y-%m-%dT%H:%M:%SZ")


def _write_evidence(path, metric, age_s=0):
    """Freshness is judged by the payload's captured_at (mtime can be
    laundered by checkout/copy), so age is encoded in the timestamp."""
    captured_at = _iso_age(age_s)
    with open(path, "w") as f:
        json.dump({"captured_at": captured_at,
                   "captured_by": "tools/tpu_watch.py",
                   "metric": metric}, f)
    return captured_at


def test_fresh_evidence_is_replayed_with_capture_tag(
        bench_mod, tmp_path, monkeypatch, capsys):
    path = tmp_path / "TPU_EVIDENCE.json"
    metric = {"metric": "count_intersect_64slice_qps", "value": 9001.5,
              "unit": "queries/sec [tpu]", "vs_baseline": 45.0}
    captured_at = _write_evidence(path, metric, age_s=600)
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    assert bench_mod._cached_evidence() is True
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 9001.5
    assert out["vs_baseline"] == 45.0
    # The replayed line must carry an honest capture-time tag.
    assert f"captured {captured_at} by tpu_watch" in out["unit"]
    assert out["unit"].startswith("queries/sec [tpu]")


def test_mtime_refresh_cannot_launder_stale_evidence(
        bench_mod, tmp_path, monkeypatch, capsys):
    """A checkout/copy resets mtime; the payload timestamp must still
    gate replay."""
    path = tmp_path / "TPU_EVIDENCE.json"
    _write_evidence(path, {"metric": "m", "value": 1.0, "unit": "u",
                           "vs_baseline": 1.0}, age_s=200000)
    now = time.time()
    os.utime(path, (now, now))  # fresh mtime, old payload
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    assert bench_mod._cached_evidence() is False
    assert capsys.readouterr().out == ""


def test_stale_evidence_is_ignored(bench_mod, tmp_path, monkeypatch,
                                   capsys):
    path = tmp_path / "TPU_EVIDENCE.json"
    _write_evidence(path, {"metric": "m", "value": 1.0, "unit": "u",
                           "vs_baseline": 1.0}, age_s=47000)
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    assert bench_mod._cached_evidence() is False
    assert capsys.readouterr().out == ""


def test_evidence_max_age_env_override(bench_mod, tmp_path, monkeypatch,
                                       capsys):
    path = tmp_path / "TPU_EVIDENCE.json"
    _write_evidence(path, {"metric": "m", "value": 1.0, "unit": "u",
                           "vs_baseline": 1.0}, age_s=3600)
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_MAX_AGE", "60")
    assert bench_mod._cached_evidence() is False
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_MAX_AGE", "7200")
    assert bench_mod._cached_evidence() is True
    assert json.loads(capsys.readouterr().out.strip())["value"] == 1.0


def test_missing_and_corrupt_evidence(bench_mod, tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH",
                       str(tmp_path / "absent.json"))
    assert bench_mod._cached_evidence() is False
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(bad))
    assert bench_mod._cached_evidence() is False
    # Metric object missing required keys.
    nometric = tmp_path / "nometric.json"
    _write_evidence(nometric, {"unit": "u"})
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(nometric))
    assert bench_mod._cached_evidence() is False
    # Unparseable capture timestamp → rejected, not crashed.
    badts = tmp_path / "badts.json"
    with open(badts, "w") as f:
        json.dump({"captured_at": "yesterday-ish",
                   "metric": {"metric": "m", "value": 1.0,
                              "unit": "u", "vs_baseline": 1.0}}, f)
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(badts))
    assert bench_mod._cached_evidence() is False


def test_detail_merge_never_clobbers_captured_sections(
        bench_mod, tmp_path, monkeypatch):
    """A skipped/failed detail run must not overwrite a previously
    captured BENCH_DETAIL.md body (watcher and driver share the file)."""
    out = tmp_path / "BENCH_DETAIL.md"
    out.write_text(
        "# Accelerator benchmark detail "
        "(captured by bench.py alongside the round metric)\n\n"
        "## suite [captured]\n```\nreal chip numbers here\n"
        "## not-a-heading inside a fence\n```\n\n"
        "## executor_qps [partial]\n```\nold partial output\n```\n\n"
        "## count10b [captured]\n```\nmore chip numbers\n```\n")
    monkeypatch.setenv("PILOSA_TPU_BENCH_DETAIL_PATH", str(out))
    monkeypatch.setenv("PILOSA_TPU_CHIP_LOCK_PATH",
                       str(tmp_path / "chip.lock"))
    # Budget of 1s: every section is skipped, so nothing captured may
    # be clobbered (and the fence-internal '## ' line must not split
    # the suite section).
    monkeypatch.setenv("PILOSA_TPU_BENCH_DETAIL", "1")
    bench_mod._capture_detail()
    text = out.read_text()
    assert "real chip numbers here" in text
    assert "## not-a-heading inside a fence" in text
    assert "more chip numbers" in text
    # An old PARTIAL body is fair game for replacement even by a skip
    # marker; sections the old file lacked get the skip marker too.
    assert "old partial output" not in text
    assert "skipped: detail budget spent" in text
    assert "## suite [captured]" in text


def test_detail_skips_when_chip_lock_busy(bench_mod, tmp_path,
                                          monkeypatch, capsys):
    import fcntl

    lockp = tmp_path / "chip.lock"
    out = tmp_path / "BENCH_DETAIL.md"
    out.write_text("## suite [captured]\n```\nkeep me\n```\n")
    holder = open(lockp, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    monkeypatch.setenv("PILOSA_TPU_CHIP_LOCK_PATH", str(lockp))
    monkeypatch.setenv("PILOSA_TPU_BENCH_DETAIL_PATH", str(out))
    monkeypatch.setenv("PILOSA_TPU_BENCH_DETAIL", "1")
    t0 = time.monotonic()
    # Zero-ish wait: patch the bounded timeout via a tiny monkeypatched
    # _chip_lock call path — use the real function with timeout by
    # invoking _capture_detail, but shrink its wait through the lock
    # being busy for only the poll interval. The function hardcodes
    # 600s, so instead call _chip_lock directly to verify busy → None.
    assert bench_mod._chip_lock(timeout=0.1) is None
    assert time.monotonic() - t0 < 30
    holder.close()
    # Lock free again: bounded acquire succeeds and must be released.
    h = bench_mod._chip_lock(timeout=5)
    assert h not in (None, "unlocked")
    bench_mod._chip_unlock(h)
    h2 = bench_mod._chip_lock(timeout=5)
    assert h2 not in (None, "unlocked")
    bench_mod._chip_unlock(h2)


def test_watcher_evidence_age_uses_payload_timestamp(tmp_path,
                                                     monkeypatch):
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "tpu_watch", os.path.join(_ROOT, "tools", "tpu_watch.py"))
    watch = ilu.module_from_spec(spec)
    spec.loader.exec_module(watch)
    ev = tmp_path / "TPU_EVIDENCE.json"
    monkeypatch.setattr(watch, "EVIDENCE", str(ev))
    assert watch.evidence_age() is None
    _write_evidence(ev, {"metric": "m", "value": 1.0, "unit": "u",
                         "vs_baseline": 1.0}, age_s=7200)
    now = time.time()
    os.utime(ev, (now, now))  # fresh mtime must not hide the real age
    age = watch.evidence_age()
    assert age is not None and 7000 < age < 7400


def test_watcher_probe_parses_backends(monkeypatch):
    """tpu_watch.probe() classifies cpu-resolution as unhealthy."""
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "tpu_watch", os.path.join(_ROOT, "tools", "tpu_watch.py"))
    watch = ilu.module_from_spec(spec)
    spec.loader.exec_module(watch)

    class FakeResult:
        def __init__(self, out, rc=0):
            self.stdout = out
            self.stderr = ""
            self.returncode = rc

    monkeypatch.setattr(watch.subprocess, "run",
                        lambda *a, **k: FakeResult("cpu 8\n"))
    ok, info = watch.probe()
    assert not ok and "cpu" in info

    monkeypatch.setattr(watch.subprocess, "run",
                        lambda *a, **k: FakeResult("tpu 1\n"))
    ok, info = watch.probe()
    assert ok and "tpu" in info


def test_tpu_evidence_block_reports_stale_with_code_delta(
        bench_mod, tmp_path, monkeypatch):
    """VERDICT r4 #7: a fallback line must still carry the newest TPU
    evidence — value, capture time, age, commits-behind — even when it
    is far too old to REPLAY as the headline."""
    path = tmp_path / "TPU_EVIDENCE.json"
    metric = {"metric": "count_intersect_64slice_qps", "value": 1234.5,
              "unit": "queries/sec [tpu]", "vs_baseline": 10.0}
    captured_at = _write_evidence(path, metric, age_s=3 * 86400)  # 3 days
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    # Far beyond max replay age: the headline replay must refuse it...
    assert bench_mod._load_evidence()[0] is None
    # ...but the report block must still surface it, with the delta.
    block = bench_mod._tpu_evidence_block()
    assert block["value"] == 1234.5
    assert block["captured_at"] == captured_at
    assert 71.5 < block["age_hours"] < 72.5
    assert isinstance(block["commits_behind"], int)  # repo has commits


def test_tpu_evidence_block_absent_file(bench_mod, tmp_path, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH",
                       str(tmp_path / "nope.json"))
    assert bench_mod._tpu_evidence_block() is None


def test_forward_metric_line_annotates_fallback(
        bench_mod, tmp_path, monkeypatch, capsys):
    """The CPU-fallback path forwards the child's metric line WITH the
    tpu_evidence block attached, so BENCH_r{N}.json carries the chip
    story explicitly."""
    import subprocess

    path = tmp_path / "TPU_EVIDENCE.json"
    _write_evidence(path, {"metric": "m", "value": 7.7, "unit": "u"},
                    age_s=100)
    monkeypatch.setenv("PILOSA_TPU_EVIDENCE_PATH", str(path))
    # Redirect the perf ledger: forwarding a FRESH measurement also
    # records a row, which must land here, not in the repo's ledger.
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("PILOSA_PERF_LEDGER", str(ledger))
    child = subprocess.CompletedProcess(
        args=[], returncode=0,
        stdout='noise\n{"metric": "m", "value": 463.0, "unit": "u '
               '[accelerator unreachable: CPU-backend fallback]"}\n')
    assert bench_mod._forward_metric_line(child, annotate_evidence=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 463.0
    assert out["tpu_evidence"]["value"] == 7.7
    assert out["tpu_evidence"]["commits_behind"] is not None
    row = json.loads(ledger.read_text().splitlines()[0])
    assert row["bench"] == "bench" and row["value"] == 463.0
    assert row["backend"] == "cpu"  # parsed from the fallback tag
