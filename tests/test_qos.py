"""QoS & admission control (pilosa_tpu/qos.py): deadline propagation
through the serving stack, priority load shedding, per-client quotas,
and peer circuit breakers — unit tests for each mechanism plus the
cluster acceptance scenarios from the issue (deadline expiry mid
fan-out must 504 within the budget; a saturated gate must shed with
429/503 + Retry-After while in-flight queries complete)."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import qos
from pilosa_tpu.server.server import Server
from pilosa_tpu.testing import free_ports


def http(method, url, body=None, headers=None):
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ------------------------------------------------------------- units

def test_token_bucket_refill_and_retry_after():
    clock = [0.0]
    b = qos.TokenBucket(rate=2.0, burst=2.0, now=clock[0])
    assert b.try_take(clock[0]) == 0.0
    assert b.try_take(clock[0]) == 0.0
    wait = b.try_take(clock[0])
    assert wait == pytest.approx(0.5)  # 1 token at 2/s
    clock[0] += 0.5
    assert b.try_take(clock[0]) == 0.0


def test_client_quotas_per_client_and_overrides():
    clock = [0.0]
    q = qos.ClientQuotas(default_qps=1.0, default_burst=1.0,
                         overrides={"vip": 0}, clock=lambda: clock[0])
    q.allow("a")
    with pytest.raises(qos.ShedError) as ei:
        q.allow("a")
    assert ei.value.status == 429 and ei.value.retry_after > 0
    q.allow("b")            # independent bucket
    for _ in range(10):
        q.allow("vip")      # qps 0 override = unlimited
    clock[0] += 1.0
    q.allow("a")            # refilled
    assert q.snapshot()["deniedTotal"] == 1


def test_quotas_disabled_by_default():
    q = qos.ClientQuotas()   # default qps 0 = off
    for _ in range(100):
        q.allow("anyone")


def test_quota_eviction_is_not_a_reset(monkeypatch):
    """Hitting the bucket-table bound must not refill every live
    client's quota (the old clear() did): full buckets evict
    losslessly, an exhausted slow-refill bucket survives and keeps
    denying."""
    monkeypatch.setattr(qos.ClientQuotas, "MAX_CLIENTS", 8)
    clock = [0.0]
    q = qos.ClientQuotas(default_qps=1.0, default_burst=1.0,
                         overrides={"limited": 0.01},
                         clock=lambda: clock[0])
    q.allow("limited")
    with pytest.raises(qos.ShedError):
        q.allow("limited")           # empty; refill takes ~100 s
    for i in range(32):              # churn ids past the table bound;
        clock[0] += 1.0              # 1 s apart so churned buckets
        q.allow(f"new-{i}")          # refill to full (lossless evict)
    with pytest.raises(qos.ShedError):
        q.allow("limited")           # live throttle state survived
    assert len(q._buckets) <= 8


def test_admission_gate_sheds_when_queue_full():
    g = qos.AdmissionGate(max_concurrent=1, queue_length=0,
                          queue_timeout=0.05)
    assert g.acquire() == 0.0
    with pytest.raises(qos.ShedError) as ei:
        g.acquire()
    assert ei.value.status == 503
    g.release()
    assert g.acquire() == 0.0
    g.release()


def test_admission_gate_internal_never_queues():
    g = qos.AdmissionGate(max_concurrent=1, queue_length=0,
                          queue_timeout=0.05)
    g.acquire()
    # Internal fan-out admits even at capacity — it must never park
    # behind (or be shed with) user traffic.
    assert g.acquire(priority=qos.PRIO_INTERNAL) == 0.0
    g.release()
    g.release()


def test_admission_gate_priority_handoff():
    """A released slot goes to the highest-priority earliest waiter:
    interactive overtakes batch that queued first."""
    g = qos.AdmissionGate(max_concurrent=1, queue_length=8,
                          queue_timeout=5.0)
    g.acquire()
    order = []
    started = threading.Barrier(3)

    def waiter(prio, name):
        started.wait()
        if name == "interactive":
            time.sleep(0.1)  # batch queues FIRST, interactive still wins
        g.acquire(priority=prio)
        order.append(name)
        time.sleep(0.02)
        g.release()

    threads = [
        threading.Thread(target=waiter, args=(qos.PRIO_BATCH, "batch")),
        threading.Thread(target=waiter,
                         args=(qos.PRIO_INTERACTIVE, "interactive")),
    ]
    for t in threads:
        t.start()
    started.wait()
    time.sleep(0.3)   # both parked in the queue
    g.release()       # hand-off begins
    for t in threads:
        t.join(timeout=10)
    assert order == ["interactive", "batch"]


def test_admission_gate_queue_timeout_sheds():
    g = qos.AdmissionGate(max_concurrent=1, queue_length=4,
                          queue_timeout=0.05)
    g.acquire()
    t0 = time.perf_counter()
    with pytest.raises(qos.ShedError) as ei:
        g.acquire()
    assert time.perf_counter() - t0 < 2.0
    assert ei.value.status == 503
    assert g.snapshot()["shedQueueTimeout"] == 1
    g.release()


def test_breaker_lifecycle():
    clock = [0.0]
    b = qos.PeerBreakers(threshold=3, cooldown=5.0,
                         clock=lambda: clock[0])
    host = "peer:10101"
    assert b.allow(host)
    for _ in range(2):
        b.record_failure(host)
    assert b.allow(host)          # under threshold: still closed
    b.record_failure(host)        # 3rd consecutive: opens
    assert not b.allow(host)
    assert b.is_open(host)
    assert host in b.open_hosts()
    clock[0] += 5.0               # cooldown elapses -> half-open
    assert b.allow(host)          # the single probe slot
    assert not b.allow(host)      # concurrent request: refused
    b.record_failure(host)        # probe failed -> reopens
    assert not b.allow(host)
    clock[0] += 5.0
    assert b.allow(host)
    b.record_success(host)        # probe succeeded -> closed
    assert b.allow(host) and b.allow(host)
    assert not b.open_hosts()
    m = b.metrics()
    assert m["breaker_open_total"] == 2
    assert m[f"breaker_state;peer:{host}"] == 0


def test_breaker_abort_probe_releases_half_open_slot():
    """An inconclusive half-open probe (budget expired mid-flight)
    must release the probe slot — not wedge the peer in HALF_OPEN."""
    clock = [0.0]
    b = qos.PeerBreakers(threshold=1, cooldown=5.0,
                         clock=lambda: clock[0])
    b.record_failure("h")
    clock[0] += 5.0
    assert b.allow("h")           # the half-open probe slot
    assert not b.allow("h")       # held
    b.abort_probe("h")            # probe ended with no verdict
    assert b.allow("h")           # next request takes the slot
    b.record_success("h")
    assert b.snapshot()["h"]["state"] == "closed"


def test_breaker_success_resets_failure_streak():
    b = qos.PeerBreakers(threshold=3)
    b.record_failure("h")
    b.record_failure("h")
    b.record_success("h")         # consecutive counter resets
    b.record_failure("h")
    b.record_failure("h")
    assert b.allow("h")


def test_deadline_scope_nests_tighter_only():
    # Deadlines are monotonic-clock instants in-process; only the
    # X-Pilosa-Deadline wire format is wall-clock.
    outer = time.monotonic() + 100
    inner = time.monotonic() + 200
    with qos.deadline_scope(outer):
        assert qos.current_deadline() == outer
        with qos.deadline_scope(inner):   # looser: outer wins
            assert qos.current_deadline() == outer
        with qos.deadline_scope(time.monotonic() - 1):
            with pytest.raises(qos.DeadlineExceeded):
                qos.check_deadline()
        assert qos.current_deadline() == outer
    assert qos.current_deadline() is None


# --------------------------------------------------- single-node HTTP

@pytest.fixture
def qserver(tmp_path):
    s = Server(str(tmp_path / "data"), bind="localhost:0",
               qos={"enabled": True, "max-concurrent": 1,
                    "queue-length": 0, "queue-timeout": 0.2,
                    # Default qps 0 (unlimited) so only the "greedy"
                    # client is rate-limited — the shed test's
                    # anonymous bursts must hit the GATE, not a quota.
                    "quotas": {"greedy": 0.5}}).open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    http("POST", f"{base}/index/i/frame/f", b"{}")
    http("POST", f"{base}/index/i/query",
         b'SetBit(frame="f", rowID=1, columnID=2)')
    yield s, base
    s.close()


def test_shed_under_load_429_503_with_retry_after(qserver):
    """Saturate the 1-slot gate from threads: in-flight queries
    complete normally, the overflow sheds 503 + Retry-After."""
    s, base = qserver
    release = threading.Event()
    in_handler = threading.Event()
    orig = s.executor.execute

    def slow_execute(*a, **kw):
        in_handler.set()
        release.wait(10)
        return orig(*a, **kw)

    s.executor.execute = slow_execute
    results = []

    def query():
        results.append(http("POST", f"{base}/index/i/query",
                            b'Count(Bitmap(frame="f", rowID=1))'))

    holder = threading.Thread(target=query)
    holder.start()
    assert in_handler.wait(10)        # one query holds the only slot
    shed = [http("POST", f"{base}/index/i/query",
                 b'Count(Bitmap(frame="f", rowID=1))')
            for _ in range(3)]
    release.set()
    holder.join(timeout=10)
    s.executor.execute = orig

    status, body, _ = results[0]
    assert status == 200 and json.loads(body)["results"] == [1]
    for status, body, headers in shed:
        assert status == 503
        assert b"overloaded" in body
        assert float(headers["Retry-After"]) > 0
    out = json.loads(http("GET", f"{base}/debug/qos")[1])
    assert out["gate"]["shedQueueFull"] == 3
    assert out["shedTotal"] == 3


def test_client_quota_429(qserver):
    s, base = qserver
    hdr = {"X-Pilosa-Client-Id": "greedy"}
    q = b'Count(Bitmap(frame="f", rowID=1))'
    first = http("POST", f"{base}/index/i/query", q, hdr)
    assert first[0] == 200
    second = http("POST", f"{base}/index/i/query", q, hdr)
    assert second[0] == 429
    assert float(second[2]["Retry-After"]) > 0
    # A different client has its own bucket.
    assert http("POST", f"{base}/index/i/query", q,
                {"X-Pilosa-Client-Id": "other"})[0] == 200


def test_expired_deadline_504(qserver):
    s, base = qserver
    q = b'Count(Bitmap(frame="f", rowID=1))'
    status, body, _ = http(
        "POST", f"{base}/index/i/query", q,
        # Wire format is unix-epoch WALL clock (converted to
        # monotonic server-side).  pilint: disable=deadline-clock
        {qos.DEADLINE_HEADER: str(time.time() - 1)})
    assert status == 504 and b"deadline exceeded" in body
    status, _, _ = http("POST", f"{base}/index/i/query", q)
    assert status == 200
    # The query is now response-cached — expiry must still 504:
    # deadline semantics cannot depend on cache state.
    status, body, _ = http(
        "POST", f"{base}/index/i/query", q,
        # Wire format is unix-epoch WALL clock (converted to
        # monotonic server-side).  pilint: disable=deadline-clock
        {qos.DEADLINE_HEADER: str(time.time() - 1)})
    assert status == 504 and b"deadline exceeded" in body


def test_bad_timeout_400(qserver):
    s, base = qserver
    q = b'Count(Bitmap(frame="f", rowID=1))'
    assert http("POST", f"{base}/index/i/query?timeout=bogus", q)[0] == 400
    assert http("POST", f"{base}/index/i/query?timeout=-1", q)[0] == 400
    # NaN/inf parse as floats but fail every expiry comparison — they
    # must 400, not run unbounded while wearing a deadline.
    assert http("POST", f"{base}/index/i/query?timeout=nan", q)[0] == 400
    assert http("POST", f"{base}/index/i/query?timeout=inf", q)[0] == 400
    assert http("POST", f"{base}/index/i/query", q,
                {qos.DEADLINE_HEADER: "nan"})[0] == 400


def test_metrics_export_qos_series(qserver):
    s, base = qserver
    # Mint a breaker entry so the per-peer state series exists.
    s.qos.breakers.record_failure("peer:1")
    body = http("GET", f"{base}/metrics")[1].decode()
    assert "pilosa_qos_shed_total" in body
    assert "pilosa_qos_queue_depth" in body
    assert 'pilosa_qos_breaker_state{peer="peer:1"} 0' in body
    out = json.loads(http("GET", f"{base}/debug/vars")[1])
    assert out["qos"]["enabled"] is True


def test_qos_disabled_is_nop(tmp_path):
    """Default config: nop tier — queries serve, /debug/qos answers
    disabled, /metrics has no qos series."""
    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    http("POST", f"{base}/index/i/frame/f", b"{}")
    assert s.qos is qos.NOP
    assert s.client.breakers is None
    status, body, _ = http("POST", f"{base}/index/i/query",
                           b'SetBit(frame="f", rowID=1, columnID=9)')
    assert status == 200
    assert json.loads(http("GET", f"{base}/debug/qos")[1]) == {
        "enabled": False}
    assert "pilosa_qos" not in http("GET", f"{base}/metrics")[1].decode()
    s.close()


def test_oversized_body_413(tmp_path):
    s = Server(str(tmp_path / "data"), bind="localhost:0",
               max_body_size=1024).open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    import http.client as hc

    host, port = s.host.rsplit(":", 1)
    # Raw socket: send headers declaring an oversized body, read the
    # refusal WITHOUT sending the body (the server must answer from
    # the Content-Length alone, never buffering).
    conn = hc.HTTPConnection(host, int(port), timeout=10)
    conn.putrequest("POST", "/index/i/query")
    conn.putheader("Content-Length", str(1 << 20))
    conn.putheader("Content-Type", "application/json")
    conn.endheaders()
    resp = conn.getresponse()
    assert resp.status == 413
    assert b"too large" in resp.read()
    conn.close()
    # At the limit: accepted.
    status, _, _ = http("POST", f"{base}/index/i/query", b" " * 100)
    assert status == 400  # parsed (empty query), not 413
    # Garbage Content-Length: 400, not a dropped connection.
    conn = hc.HTTPConnection(host, int(port), timeout=10)
    conn.putrequest("POST", "/index/i/query")
    conn.putheader("Content-Length", "banana")
    conn.endheaders()
    assert conn.getresponse().status == 400
    conn.close()
    # Fragment restore is exempt from the cap (backup tars are big);
    # an oversized declared body reaches the handler (and 400s on the
    # garbage payload, not 413).
    status, body, _ = http("POST",
                           f"{base}/fragment/data?index=i&frame=f",
                           b"x" * 4096)
    assert status != 413
    s.close()
    # 0 disables the limit entirely (docs/configuration.md contract).
    from pilosa_tpu.config import Config

    cfg = Config()
    cfg.max_body_size = 0
    cfg.validate()


def test_minitoml_parses_dotted_qos_quotas_table():
    """The vendored TOML fallback must parse the documented
    [qos.quotas] nested table — the form Config.to_toml emits."""
    from pilosa_tpu.utils import minitoml

    out = minitoml.loads(
        '[qos]\nenabled = true\n\n[qos.quotas]\n"etl" = 0.5\n')
    assert out == {"qos": {"enabled": True, "quotas": {"etl": 0.5}}}


def test_negative_content_length_400(tmp_path):
    """Content-Length: -1 must 400, never reach rfile.read(-1) (an
    unbounded until-EOF buffer past the 413 gate)."""
    import http.client as hc

    s = Server(str(tmp_path / "data"), bind="localhost:0",
               max_body_size=1024).open()
    host, port = s.host.rsplit(":", 1)
    conn = hc.HTTPConnection(host, int(port), timeout=10)
    conn.putrequest("POST", "/index/i/query")
    conn.putheader("Content-Length", "-1")
    conn.endheaders()
    assert conn.getresponse().status == 400
    conn.close()
    s.close()


def test_input_definition_malformed_frame_400(tmp_path):
    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    status, body, _ = http(
        "POST", f"{base}/index/i/input-definition/x",
        json.dumps({"frames": [{}],
                    "fields": [{"name": "columnID",
                                "primaryKey": True}]}).encode())
    assert status == 400 and b"missing field: name" in body
    s.close()


def test_keyerror_is_500_not_400(tmp_path):
    """A genuine handler bug (internal KeyError) must surface as 500;
    a missing request field is explicit 400 validation."""
    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    http("POST", f"{base}/index/i/frame/f", b"{}")
    # Missing required fields in the body -> explicit 400.
    status, body, _ = http("POST", f"{base}/import",
                           json.dumps({"frame": "f"}).encode())
    assert status == 400 and b"missing field: index" in body
    status, body, _ = http("POST", f"{base}/import-value",
                           json.dumps({"index": "i", "frame": "f"}).encode())
    assert status == 400 and b"missing field" in body
    # attr-diff blocks missing id/checksum: caller's 400 too.
    status, body, _ = http("POST", f"{base}/index/i/attr/diff",
                           json.dumps({"blocks": [{}]}).encode())
    assert status == 400 and b"missing field: id" in body
    status, body, _ = http("POST", f"{base}/index/i/frame/f/attr/diff",
                           json.dumps({"blocks": [{"id": 1}]}).encode())
    assert status == 400 and b"missing field: checksum" in body
    # An internal bug raising KeyError -> 500, not the caller's fault.
    def buggy(params, qp, body, headers):
        raise KeyError("internal-dict-key")
    s.handler.get_version = buggy
    s.handler.routes = s.handler._build_routes()
    status, body, _ = http("GET", f"{base}/version")
    assert status == 500
    s.close()


# -------------------------------------------------------- cluster

def test_deadline_expiry_mid_fanout_504_within_budget(tmp_path):
    """2-node cluster, one node stalls: the coordinator must return
    504 within the request budget — not after the flat 30 s internal
    client timeout."""
    from pilosa_tpu import SLICE_WIDTH

    ports = free_ports(2)
    hosts = [f"127.0.0.1:{p}" for p in ports]
    qcfg = {"enabled": True}
    release = threading.Event()
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=1, anti_entropy_interval=0, polling_interval=0,
               qos=qcfg).open()
        for i in range(2)
    ]
    try:
        base = f"http://{servers[0].host}"
        http("POST", f"{base}/index/i", b"{}")
        http("POST", f"{base}/index/i/frame/f", b"{}")
        # Bits across enough slices that both nodes own some.
        bits = "".join(
            f'SetBit(frame="f", rowID=1, columnID={c * SLICE_WIDTH})'
            for c in range(8))
        status, _, _ = http("POST", f"{base}/index/i/query", bits.encode())
        assert status == 200

        for s in servers[1:]:
            orig = s.executor.execute

            def stalled(*a, _orig=orig, **kw):
                release.wait(20)   # longer than the budget, < test timeout
                return _orig(*a, **kw)

            s.executor.execute = stalled

        t0 = time.perf_counter()
        status, body, _ = http(
            "POST", f"{base}/index/i/query?timeout=1.5",
            b'Count(Bitmap(frame="f", rowID=1))')
        elapsed = time.perf_counter() - t0
        release.set()
        assert status == 504, body
        assert b"deadline exceeded" in body
        # Well within the budget's order of magnitude — NOT the flat
        # 30 s client timeout.
        assert elapsed < 10
    finally:
        release.set()
        for s in servers:
            s.close()


def test_breaker_opens_on_dead_peer_and_fails_fast(tmp_path):
    """Repeated transport failures to a dead peer open its breaker;
    the next call fails immediately (no dial), and the executor's
    up-front routing skips the dead host when replicas cover it."""
    from pilosa_tpu.cluster.client import ClientError, InternalClient
    from pilosa_tpu.cluster.cluster import Cluster, Node

    (dead_port,) = free_ports(1)
    dead = Node(f"127.0.0.1:{dead_port}")
    brk = qos.PeerBreakers(threshold=3, cooldown=60.0)
    client = InternalClient(timeout=2, breakers=brk)
    for _ in range(3):
        with pytest.raises(ClientError):
            client._do("GET", f"http://{dead.host}/id")
    assert brk.is_open(dead.host)
    t0 = time.perf_counter()
    with pytest.raises(ClientError) as ei:
        client._do("GET", f"http://{dead.host}/id")
    assert ei.value.breaker_open
    assert time.perf_counter() - t0 < 0.1   # no dial, instant refusal
    # Probes bypass the breaker (the recovery path still dials).
    assert client.probe(dead, timeout=1) is False
    # Routing: healthy_nodes drops the open-breaker peer.
    cluster = Cluster(nodes=[Node("up:1"), dead])
    cluster.breakers = brk
    assert cluster.healthy_nodes() == [Node("up:1")]
    assert cluster.status()["breakerOpen"] == [dead.host]
    client.close()


def test_budget_timeout_does_not_open_breaker():
    """A deadline-bounded timeout proves the budget spent, not the
    peer dead: it must not feed the breaker. A health-timeout (the
    configured client timeout, no deadline) still does."""
    import socket as sk

    from pilosa_tpu.cluster.client import ClientError, InternalClient
    from pilosa_tpu.cluster.cluster import Node

    srv = sk.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)   # accepts connections, never answers
    host = f"127.0.0.1:{srv.getsockname()[1]}"
    node = Node(host)
    try:
        brk = qos.PeerBreakers(threshold=1, cooldown=60.0)
        client = InternalClient(timeout=30, breakers=brk)
        with pytest.raises(qos.DeadlineExceeded):
            client.execute_query(node, "i", 'Count(Bitmap(rowID=1))',
                                 remote=True,
                                 deadline=time.monotonic() + 0.2)
        assert not brk.is_open(host)    # budget timeout: no breaker
        client.close()
        client2 = InternalClient(timeout=0.2, breakers=brk)
        with pytest.raises(ClientError):
            client2.execute_query(node, "i", 'Count(Bitmap(rowID=1))',
                                  remote=True)
        assert brk.is_open(host)        # health timeout: opens
        client2.close()
    finally:
        srv.close()


def test_breaker_half_open_recovery(tmp_path):
    """After the cooldown one probe goes through; a success closes the
    breaker and normal traffic resumes."""
    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    try:
        from pilosa_tpu.cluster.client import InternalClient
        from pilosa_tpu.cluster.cluster import Node

        brk = qos.PeerBreakers(threshold=1, cooldown=0.05)
        client = InternalClient(timeout=2, breakers=brk)
        node = Node(s.host)
        brk.record_failure(s.host)          # open immediately
        assert brk.is_open(s.host)
        time.sleep(0.06)                    # cooldown elapses
        status, _, _ = client._do("GET", f"http://{s.host}/id")
        assert status == 200                # half-open probe succeeded
        assert not brk.is_open(s.host)
        assert brk.snapshot()[s.host]["state"] == "closed"
        client.close()
    finally:
        s.close()


def test_internal_priority_bypasses_saturated_gate(tmp_path):
    """A remote (internal fan-out) query admits even when the gate is
    saturated with user traffic — stamped by the internal client."""
    s = Server(str(tmp_path / "data"), bind="localhost:0",
               qos={"enabled": True, "max-concurrent": 1,
                    "queue-length": 0, "queue-timeout": 0.2}).open()
    base = f"http://{s.host}"
    http("POST", f"{base}/index/i", b"{}")
    http("POST", f"{base}/index/i/frame/f", b"{}")
    http("POST", f"{base}/index/i/query",
         b'SetBit(frame="f", rowID=1, columnID=2)')
    release = threading.Event()
    in_handler = threading.Event()
    orig = s.executor.execute
    stalled_once = threading.Event()

    def slow_execute(index, query, **kw):
        # Only the FIRST query stalls (it occupies the gate's one
        # slot); the internal-priority query must run through.
        if not stalled_once.is_set():
            stalled_once.set()
            in_handler.set()
            release.wait(10)
        return orig(index, query, **kw)

    s.executor.execute = slow_execute
    t = threading.Thread(target=http, args=(
        "POST", f"{base}/index/i/query",
        b'Count(Bitmap(frame="f", rowID=1))'))
    t.start()
    assert in_handler.wait(10)
    # user-class overflow sheds...
    assert http("POST", f"{base}/index/i/query",
                b'Count(Bitmap(frame="f", rowID=1))')[0] == 503
    # ...but the internal class admits.
    status, _, _ = http("POST", f"{base}/index/i/query",
                        b'Count(Bitmap(frame="f", rowID=1))',
                        {qos.PRIORITY_HEADER: "internal"})
    assert status == 200
    release.set()
    t.join(timeout=10)
    s.executor.execute = orig
    s.close()
