"""Anti-entropy tests: divergent replicas converge after a SyncHolder
pass (analog of holder_test.go's HolderSyncer suite)."""
import json
import urllib.request

import pytest

from pilosa_tpu.server.server import Server


from pilosa_tpu.testing import free_ports  # noqa: E402


def query(host, index, q):
    req = urllib.request.Request(f"http://{host}/index/{index}/query",
                                 data=q.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["results"]


@pytest.fixture
def cluster2(tmp_path):
    ports = free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=2, anti_entropy_interval=0,
               polling_interval=0).open()
        for i in range(2)
    ]
    yield servers
    for s in servers:
        s.close()


def test_fragment_sync_converges(cluster2):
    a, b = cluster2
    # Same schema on both (broadcast).
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)

    # Diverge the replicas by writing directly to each holder (bypassing
    # the replicated write path).
    fa = a.holder.index("i").frame("f")
    fb = b.holder.index("i").frame("f")
    fa.set_bit("standard", 1, 10)
    fa.set_bit("standard", 1, 11)
    fb.set_bit("standard", 1, 11)
    fb.set_bit("standard", 1, 12)
    fb.set_bit("standard", 2, 500)

    # Row attrs diverge too.
    fa.row_attr_store.set_attrs(1, {"label": "from-a"})
    # Column attrs.
    a.holder.index("i").column_attr_store.set_attrs(10, {"c": 1})

    a.syncer.sync_holder()
    b.syncer.sync_holder()

    # Bits: majority-of-2 = union.
    for node in (a, b):
        assert query(node.host, "i",
                     'Bitmap(frame="f", rowID=1)')[0]["bits"] == [10, 11, 12]
        assert query(node.host, "i",
                     'Bitmap(frame="f", rowID=2)')[0]["bits"] == [500]

    # Attrs replicated both directions.
    assert fb.row_attr_store.attrs(1) == {"label": "from-a"}
    assert b.holder.index("i").column_attr_store.attrs(10) == {"c": 1}


def test_sync_scoped_to_replicas_no_data_loss(tmp_path):
    """Regression: with replica_n=1 on a 3-node cluster, non-replica
    nodes must NOT participate in the majority merge (they'd vote every
    bit of the owner out of consensus)."""
    ports = free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=1, anti_entropy_interval=0,
               polling_interval=0).open()
        for i in range(3)
    ]
    try:
        a = servers[0]
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
            timeout=10)
        for col in (1, 2, 3):
            query(a.host, "i", f'SetBit(frame="f", rowID=1, columnID={col})')
        assert query(a.host, "i", 'Count(Bitmap(frame="f", rowID=1))') == [3]

        for s in servers:
            s.syncer.sync_holder()

        # Bits must survive the anti-entropy pass on every coordinator.
        for s in servers:
            assert query(s.host, "i",
                         'Count(Bitmap(frame="f", rowID=1))') == [3], s.host
    finally:
        for s in servers:
            s.close()


def test_sync_creates_missing_fragment(cluster2):
    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    # Only node A has any data.
    a.holder.index("i").frame("f").set_bit("standard", 3, 42)

    b.syncer.sync_holder()  # B pulls the missing bits
    assert query(b.host, "i", 'Count(Bitmap(frame="f", rowID=3))') == [1]
