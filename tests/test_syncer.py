"""Anti-entropy tests: divergent replicas converge after a SyncHolder
pass (analog of holder_test.go's HolderSyncer suite)."""
import json
import urllib.request

import pytest

from pilosa_tpu.server.server import Server


from pilosa_tpu.testing import free_ports  # noqa: E402


def query(host, index, q):
    req = urllib.request.Request(f"http://{host}/index/{index}/query",
                                 data=q.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["results"]


@pytest.fixture
def cluster2(tmp_path):
    ports = free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=2, anti_entropy_interval=0,
               polling_interval=0).open()
        for i in range(2)
    ]
    yield servers
    for s in servers:
        s.close()


def test_fragment_sync_converges(cluster2):
    a, b = cluster2
    # Same schema on both (broadcast).
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)

    # Diverge the replicas by writing directly to each holder (bypassing
    # the replicated write path).
    fa = a.holder.index("i").frame("f")
    fb = b.holder.index("i").frame("f")
    fa.set_bit("standard", 1, 10)
    fa.set_bit("standard", 1, 11)
    fb.set_bit("standard", 1, 11)
    fb.set_bit("standard", 1, 12)
    fb.set_bit("standard", 2, 500)

    # Row attrs diverge too.
    fa.row_attr_store.set_attrs(1, {"label": "from-a"})
    # Column attrs.
    a.holder.index("i").column_attr_store.set_attrs(10, {"c": 1})

    a.syncer.sync_holder()
    b.syncer.sync_holder()

    # Bits: majority-of-2 = union.
    for node in (a, b):
        assert query(node.host, "i",
                     'Bitmap(frame="f", rowID=1)')[0]["bits"] == [10, 11, 12]
        assert query(node.host, "i",
                     'Bitmap(frame="f", rowID=2)')[0]["bits"] == [500]

    # Attrs replicated both directions.
    assert fb.row_attr_store.attrs(1) == {"label": "from-a"}
    assert b.holder.index("i").column_attr_store.attrs(10) == {"c": 1}


def test_sync_scoped_to_replicas_no_data_loss(tmp_path):
    """Regression: with replica_n=1 on a 3-node cluster, non-replica
    nodes must NOT participate in the majority merge (they'd vote every
    bit of the owner out of consensus)."""
    ports = free_ports(3)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=1, anti_entropy_interval=0,
               polling_interval=0).open()
        for i in range(3)
    ]
    try:
        a = servers[0]
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
            timeout=10)
        for col in (1, 2, 3):
            query(a.host, "i", f'SetBit(frame="f", rowID=1, columnID={col})')
        assert query(a.host, "i", 'Count(Bitmap(frame="f", rowID=1))') == [3]

        for s in servers:
            s.syncer.sync_holder()

        # Bits must survive the anti-entropy pass on every coordinator.
        for s in servers:
            assert query(s.host, "i",
                         'Count(Bitmap(frame="f", rowID=1))') == [3], s.host
    finally:
        for s in servers:
            s.close()


def test_sync_creates_missing_fragment(cluster2):
    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    # Only node A has any data.
    a.holder.index("i").frame("f").set_bit("standard", 3, 42)

    b.syncer.sync_holder()  # B pulls the missing bits
    assert query(b.host, "i", 'Count(Bitmap(frame="f", rowID=3))') == [1]


def test_digest_precheck_skips_block_walk_when_identical(cluster2):
    """Identical replicas must sync with ZERO block fetches: the
    fragment-level digest pre-check (one value per replica) agrees and
    the per-block checksum walk never runs (beyond-ref: the reference
    walks every block unconditionally, fragment.go:1703-1782).
    Divergent replicas must still take the full path and converge —
    the pre-check may only skip work, never repairs."""
    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    # Identical content on both replicas, several slices, mixed
    # resident/evicted residency (digest must not depend on it).
    import numpy as np

    from pilosa_tpu import SLICE_WIDTH

    for holder in (a.holder, b.holder):
        fr = holder.index("i").frame("f")
        rng = np.random.default_rng(9)
        for s in range(4):
            cols = (rng.choice(3000, size=200, replace=False)
                    .astype(np.uint64) + s * SLICE_WIDTH)
            fr.import_bits(np.full(200, 1, dtype=np.uint64), cols)
    for s in range(0, 4, 2):  # evict half the fragments on one side
        a.holder.fragment("i", "f", "standard", s).unload()

    blocks_calls = []
    orig_blocks = a.syncer.client.fragment_blocks

    def counting_blocks(*args, **kw):
        blocks_calls.append(args)
        return orig_blocks(*args, **kw)

    a.syncer.client.fragment_blocks = counting_blocks
    try:
        a.syncer.sync_holder()
    finally:
        a.syncer.client.fragment_blocks = orig_blocks
    assert blocks_calls == [], \
        f"identical replicas fetched blocks: {blocks_calls[:3]}"

    # EXACTNESS (the old (key, cardinality) digest's systematic blind
    # spot, which needed a periodic unconditional walk): a divergence
    # that preserves every container's cardinality on both replicas —
    # same row, same container, different column — must flip the
    # content-true digest and take the walk on the FIRST pass.
    a.holder.fragment("i", "f", "standard", 0).set_bit(5, 100)
    b.holder.fragment("i", "f", "standard", 0).set_bit(5, 101)
    a.syncer.client.fragment_blocks = counting_blocks
    try:
        a.syncer.sync_holder()
    finally:
        a.syncer.client.fragment_blocks = orig_blocks
    assert blocks_calls, \
        "cardinality-preserving divergence must walk on pass 1"
    # The walk repaired it: both replicas now hold both bits.
    assert query(a.host, "i", 'Count(Bitmap(frame="f", rowID=5))') == [2]
    assert query(b.host, "i", 'Count(Bitmap(frame="f", rowID=5))') == [2]
    blocks_calls.clear()

    # Now diverge one bit; the digest differs and the walk repairs it.
    b.holder.index("i").frame("f").set_bit("standard", 1, 7_777)
    blocks_calls.clear()
    a.syncer.client.fragment_blocks = counting_blocks
    try:
        a.syncer.sync_holder()
    finally:
        a.syncer.client.fragment_blocks = orig_blocks
    assert blocks_calls, "divergent replicas must take the block walk"
    assert 7_777 in query(a.host, "i",
                          'Bitmap(frame="f", rowID=1)')[0]["bits"]


def test_fragment_digest_residency_invariance(tmp_path):
    """digest() must be identical for the same content whether the
    fragment is resident, evicted (lazy header), or reopened — and for
    replicas that reached the content through different write orders
    (op log vs snapshot encodings)."""
    import numpy as np

    from pilosa_tpu.storage.fragment import Fragment

    pa = str(tmp_path / "a")
    pb = str(tmp_path / "b")
    fa = Fragment(pa, "i", "f", "standard", 0).open()
    fb = Fragment(pb, "i", "f", "standard", 0).open()
    rng = np.random.default_rng(4)
    cols = rng.choice(100_000, size=5_000, replace=False).astype(np.uint64)
    # a: one bulk import (snapshot encoding); b: two chunks (op log on
    # top of a snapshot) + an extra bit that is then cleared.
    fa.import_bits(np.full(5_000, 3, dtype=np.uint64), cols)
    fb.import_bits(np.full(2_500, 3, dtype=np.uint64), cols[:2_500])
    fb.snapshot()
    fb.import_bits(np.full(2_500, 3, dtype=np.uint64), cols[2_500:])
    fb.set_bit(3, 999_999)
    fb.clear_bit(3, 999_999)
    d = fa.digest()
    assert fb.digest() == d
    fa.unload()
    assert fa.digest() == d, "evicted digest must match resident"
    fb.unload()
    assert fb.digest() == d
    fa.close()
    fb.close()


def test_digest_route_miss_falls_through_to_walk(cluster2):
    """A mixed-version peer without the /fragment/digest route answers
    a generic 404 ('not found', not 'fragment not found'): the syncer
    must NOT read that as the canonical empty digest — it falls
    through to the unconditional block walk (advice r4)."""
    from pilosa_tpu.cluster.client import ClientError

    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    # Local side EMPTY (matches what the bug would skip), peer has a
    # bit the sync must pull.
    b.holder.index("i").frame("f").set_bit("standard", 1, 42)
    a.holder.index("i").frame("f")  # frame exists, fragment empty

    def route_missing(*args, **kw):
        raise ClientError("peer: not found", status=404)

    blocks_calls = []
    orig_blocks = a.syncer.client.fragment_blocks

    def counting_blocks(*args, **kw):
        blocks_calls.append(args)
        return orig_blocks(*args, **kw)

    a.syncer.client.fragment_digest = route_missing
    a.syncer.client.fragment_blocks = counting_blocks
    try:
        a.syncer.sync_holder()
    finally:
        a.syncer.client.fragment_blocks = orig_blocks
    assert blocks_calls, "route-miss 404 must take the block walk"
    assert query(a.host, "i", 'Count(Bitmap(frame="f", rowID=1))') == [1]


def test_cluster_topn_discovery_memo_per_node(cluster2):
    """Round 5 (VERDICT r4 #4): the TopN discovery memo now covers
    clusters — each node memoizes ONLY its own slice subset, validated
    by its own epoch, so no cross-node invalidation protocol exists to
    get wrong. Writes landing on either node must invalidate exactly
    that node's entries and show up in the next TopN."""
    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    from pilosa_tpu import SLICE_WIDTH

    # Rows across 4 slices; replicated write path (via HTTP) so both
    # replicas hold the data and max_slice propagates.
    for s in range(4):
        for col in range(3):
            query(a.host, "i", f'SetBit(frame="f", rowID=1, '
                               f'columnID={s * SLICE_WIDTH + col})')
        query(a.host, "i", f'SetBit(frame="f", rowID=2, '
                           f'columnID={s * SLICE_WIDTH})')

    top = query(a.host, "i", 'TopN(frame="f", n=2)')[0]
    assert [p["id"] for p in top] == [1, 2]
    assert [p["count"] for p in top] == [12, 4]
    # Both nodes should now hold discovery-memo entries for their own
    # subsets (the coordinator for its primaries, the peer for the
    # remote subquery it served).
    total_entries = (len(getattr(a.executor, "_topn_disc_memo", {}))
                     + len(getattr(b.executor, "_topn_disc_memo", {})))
    assert total_entries >= 1, "no node memoized its discovery walk"

    # A write through the normal replicated path must invalidate the
    # owning node's entry: the next TopN sees the new count.
    query(a.host, "i", f'SetBit(frame="f", rowID=2, '
                       f'columnID={2 * SLICE_WIDTH + 77})')
    top = query(a.host, "i", 'TopN(frame="f", n=2)')[0]
    assert [p["count"] for p in top] == [12, 5]

    # The structural property the cluster extension rests on: NO memo
    # entry on either node may span a slice that node would not
    # execute itself (coordinator = its primary slices; remote server
    # = the subset handed to it). An entry covering another node's
    # data could not be invalidated by the local epoch. (A shared-
    # process epoch makes staleness itself unobservable here — both
    # Servers share fragment.py's module globals — so assert the
    # invariant that guarantees it in real multi-process deployments.)
    for node in (a, b):
        own_primary = {
            s for s in range(4)
            if node.cluster.fragment_nodes("i", s)[0].host == node.host}
        for (_, _, _, key_slices) in getattr(
                node.executor, "_topn_disc_memo", {}):
            assert set(key_slices) <= own_primary, \
                (node.host, key_slices, own_primary)


def test_sync_under_live_writes_converges_and_loses_nothing(cluster2):
    """Anti-entropy runs every 10 minutes against LIVE traffic in
    production; these passes must never lose acked writes or crash,
    whatever interleaving of digest computation, block walks, and
    mutations occurs (§5.2 race coverage — the digest memo is
    version-keyed, the walk reads epoch-consistent block snapshots).
    Drive concurrent writers THROUGH both coordinators while both
    nodes run sync passes, then quiesce, run one final pass each way,
    and assert full convergence including every acked bit."""
    import threading

    a, b = cluster2
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i", data=b"{}", method="POST"), timeout=10)
    urllib.request.urlopen(urllib.request.Request(
        f"http://{a.host}/index/i/frame/f", data=b"{}", method="POST"),
        timeout=10)
    from pilosa_tpu import SLICE_WIDTH

    acked = []
    acked_mu = threading.Lock()
    stop = threading.Event()
    errs = []

    def writer(server, tid):
        k = 0
        while not stop.is_set() and k < 120:
            k += 1
            col = (tid * 7 + k * 13) % (4 * SLICE_WIDTH)
            try:
                res = query(server.host, "i",
                            f'SetBit(frame="f", rowID={tid}, '
                            f'columnID={col})')
                assert res == [True] or res == [False]
                with acked_mu:
                    acked.append((tid, col))
            except Exception as exc:  # noqa: BLE001
                errs.append(repr(exc))
                return

    def syncer_loop(server):
        for _ in range(6):
            if stop.is_set():
                return
            try:
                server.syncer.sync_holder()
            except Exception as exc:  # noqa: BLE001
                errs.append(f"sync: {exc!r}")
                return

    threads = ([threading.Thread(target=writer, args=(a, 1)),
                threading.Thread(target=writer, args=(b, 2)),
                threading.Thread(target=syncer_loop, args=(a,)),
                threading.Thread(target=syncer_loop, args=(b,))])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "hung under concurrent sync+writes"
    stop.set()
    assert not errs, errs[:3]

    # Quiesce: one final pass each way must reach full agreement.
    a.syncer.sync_holder()
    b.syncer.sync_holder()
    for row in (1, 2):
        want = sorted({c for t, c in acked if t == row})
        # Compare via the query path (authoritative, attr-free).
        ca = query(a.host, "i", f'Count(Bitmap(frame="f", rowID={row}))')
        cb = query(b.host, "i", f'Count(Bitmap(frame="f", rowID={row}))')
        assert ca == cb, (row, ca, cb)
        assert ca[0] >= len(want), (row, ca, len(want))
        bm_a = query(a.host, "i", f'Bitmap(frame="f", rowID={row})')
        cols_a = set(bm_a[0]["bits"])
        missing = [c for c in want if c not in cols_a]
        assert not missing, (row, missing[:5])
        # And the digests agree — the steady state is re-provable.
    for s in range(4):
        fa = a.holder.fragment("i", "f", "standard", s)
        fb = b.holder.fragment("i", "f", "standard", s)
        if fa is not None and fb is not None:
            assert fa.digest() == fb.digest(), s
