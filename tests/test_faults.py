"""Chaos suite: deterministic fault injection end-to-end.

Drives the failpoint registry (pilosa_tpu/faults.py) through every
layer it instruments — disk faults must fail-stop (never corrupt or
acknowledge-then-lose), fan-out faults must degrade per the existing
failover/breaker semantics, drain must hold the listener open for
in-flight queries, and a kill mid-drain must still pass the crash-soak
invariant. Marked ``faults`` (``make chaos`` runs just these; they run
in ``make test`` too).
"""
import errno
import io
import json
import os
import signal
import subprocess
import sys
import tarfile
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import SLICE_WIDTH, faults
from pilosa_tpu import errors as perr
from pilosa_tpu.testing import ServerCluster, TestFragment, TestHolder

pytestmark = pytest.mark.faults


@pytest.fixture
def faultreg():
    """Fresh enabled registry, restored to the shared nop afterward —
    an armed point leaking into another test would be chaos of the
    wrong kind."""
    faults.disable()
    reg = faults.enable()
    try:
        yield reg
    finally:
        faults.disable()


def _post(host, path, body=b"", timeout=30):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    return urllib.request.urlopen(req, timeout=timeout)


def _query(host, index, q, timeout=30):
    return json.loads(
        _post(host, f"/index/{index}/query", q.encode(),
              timeout=timeout).read())["results"]


# ------------------------------------------------------------- registry

def test_spec_actions_and_triggers(faultreg):
    faultreg.configure(
        "a.b=error(ENOSPC):after=1:count=2,c.d=delay(0):p=1.0,e.f=corrupt")
    assert faultreg.fire("a.b") is None          # after=1 skips hit 1
    for _ in range(2):                           # count=2 fires twice
        with pytest.raises(OSError) as ei:
            faultreg.fire("a.b")
        assert ei.value.errno == errno.ENOSPC
    assert faultreg.fire("a.b") is None          # exhausted
    assert faultreg.fire("c.d") == "delay"
    assert faultreg.fire("e.f") == "corrupt"
    assert faultreg.fire("never.configured") is None
    m = faultreg.metrics()
    assert m["triggered_total"] == 4
    assert m["triggered_total;point:a.b"] == 2
    snap = faultreg.snapshot()
    assert snap["enabled"] and snap["points"]["a.b"]["fired"] == 2


def test_probability_uses_injectable_rand():
    rolls = iter([0.9, 0.1])
    reg = faults.FaultRegistry(_rand=lambda: next(rolls))
    reg.configure("x.y=corrupt:p=0.5")
    assert reg.fire("x.y") is None       # 0.9 >= 0.5: no fire
    assert reg.fire("x.y") == "corrupt"  # 0.1 <  0.5: fires


def test_bad_specs_rejected():
    for bad in ("noequals", "a.b=explode", "a.b=error(NOTANERRNO)",
                "a.b=delay(-1)", "a.b=corrupt:p=2.0", "a.b=corrupt:zz=1"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_disabled_default_is_nop():
    faults.disable()
    assert faults.ACTIVE.enabled is False
    assert faults.ACTIVE.fire("anything") is None
    assert faults.ACTIVE.snapshot() == {"enabled": False}
    with pytest.raises(RuntimeError):
        faults.ACTIVE.configure("a.b=corrupt")


def test_config_faults_and_drain_timeout():
    from pilosa_tpu.config import Config

    cfg = Config.load(env={})
    assert cfg.drain_timeout == 5.0 and cfg.faults["enabled"] is False
    cfg = Config.load(env={"PILOSA_DRAIN_TIMEOUT": "2.5",
                           "PILOSA_FAULTS": "a.b=corrupt"})
    assert cfg.drain_timeout == 2.5
    assert cfg.faults == {"enabled": True, "spec": "a.b=corrupt"}
    assert "drain-timeout = 2.5" in cfg.to_toml()
    assert "[faults]" in cfg.to_toml()
    cfg.faults["spec"] = "broken spec"
    with pytest.raises(ValueError):
        cfg.validate()
    cfg.faults["spec"] = ""
    cfg.drain_timeout = -1
    with pytest.raises(ValueError):
        cfg.validate()


# ------------------------------------------------- disk-fault hardening

def test_append_error_fail_stops_fragment(faultreg):
    with TestFragment() as f:
        f.set_bit(1, 10)
        faultreg.configure("fragment.append.fsync=error(ENOSPC):count=1")
        with pytest.raises(perr.ErrFragmentFailStop):
            f.set_bit(1, 11)
        # The failed write was never applied: memory stays on the
        # acknowledged prefix, reads keep serving.
        assert f.row_count(1) == 1
        assert list(f.row_words(1).nonzero()[0]) == [0]
        # Latched: subsequent writes are rejected even though the
        # injected fault is exhausted (count=1).
        with pytest.raises(perr.ErrFragmentFailStop):
            f.set_bit(1, 12)
        with pytest.raises(perr.ErrFragmentFailStop):
            f.import_bits([2], [20])
        # Clean recovery on reopen.
        f.reopen()
        assert f.row_count(1) == 1
        assert f.set_bit(1, 11) is True
        f.reopen()
        assert f.row_count(1) == 2


def test_import_enospc_never_acknowledge_then_lose(faultreg):
    with TestFragment() as f:
        faultreg.configure("fragment.append.fsync=error(ENOSPC):count=1")
        with pytest.raises(perr.ErrFragmentFailStop):
            f.import_bits([1, 1, 2], [3, 4, 5])
        assert f.count() == 0          # not acknowledged...
        f.reopen()
        assert f.count() == 0          # ...and not resurrected


def test_import_snapshot_failure_rolls_back(faultreg):
    with TestFragment() as f:
        f.set_bit(1, 1)
        f.op_n = 3000  # force the next import onto the snapshot branch
        faultreg.configure("fragment.snapshot.rename=error(ENOSPC)")
        with pytest.raises(perr.ErrFragmentFailStop):
            f.import_bits([5], [9])
        # Rolled back to the durable file: the errored import can
        # never be read back as if acknowledged.
        assert 5 not in f.rows()
        assert f.row_count(1) == 1


def test_snapshot_failure_leaves_prior_file_intact(faultreg):
    with TestFragment() as f:
        f.import_bits([1, 1, 2], [5, 6, 7])
        f.snapshot()
        before = open(f.path, "rb").read()
        faultreg.configure("fragment.snapshot.rename=error(EIO)")
        with pytest.raises(OSError):
            f.snapshot()
        assert open(f.path, "rb").read() == before   # byte-identical
        assert not os.path.exists(f.path + ".snapshotting")
        assert f.count() == 3                        # keeps serving
        assert f._failed is None                     # NOT fail-stopped
        faultreg.clear("fragment.snapshot.rename")
        f.snapshot()                                 # retry succeeds
        assert f.op_n == 0


def test_post_append_snapshot_failure_keeps_acknowledged_write(faultreg):
    """A failed housekeeping snapshot (op log over threshold) must not
    fail the write that triggered it — the op log holds it."""
    with TestFragment() as f:
        f.set_bit(1, 1)
        f.op_n = 3000  # over threshold: next set_bit tries to snapshot
        faultreg.configure("fragment.snapshot.rename=error(ENOSPC)")
        assert f.set_bit(2, 2) is True   # acknowledged despite ENOSPC
        assert f._failed is None
        f.reopen()
        assert f.row_count(2) == 1       # durable via the op log


def test_unreadable_fragment_quarantined(faultreg):
    with TestFragment() as f:
        f.set_bit(1, 1)
        path = f.path
        f.close()
        with open(path, "wb") as fh:
            fh.write(b"garbage, not a roaring file")
        f.open()
        assert f.count() == 0                      # serves empty
        assert os.path.exists(path + ".corrupt")   # original kept aside
        assert f.set_bit(2, 2) is True             # fresh file writable
        f.reopen()
        assert f.row_count(2) == 1


def test_truncated_file_with_valid_header_quarantines(faultreg):
    """Real-world rot: a truncated file whose magic/version/key_n
    survive. Decoding fails past the header (struct.error territory,
    NOT a ValueError subclass) — it must quarantine, not 500
    forever."""
    with TestFragment() as f:
        f.import_bits(list(range(5)), [3, 4, 5, 6, 7])
        f.snapshot()
        path = f.path
        f.close()
        data = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(data[:10])  # header intact, metas cut short
        f.open()
        assert f.count() == 0
        assert os.path.exists(path + ".corrupt")


def test_restore_clears_fail_stop_latch(faultreg):
    """Restoring over a fail-stopped fragment IS the repair: it
    replaces memory and file wholesale, so the read-only latch must
    clear — writes work without a process restart."""
    with TestFragment() as f:
        f.set_bit(1, 10)
        backup = io.BytesIO()
        f.write_to(backup)
        faultreg.configure("fragment.append.fsync=error(ENOSPC):count=1")
        with pytest.raises(perr.ErrFragmentFailStop):
            f.set_bit(1, 11)
        with pytest.raises(perr.ErrFragmentFailStop):
            f.set_bit(1, 12)  # latched
        backup.seek(0)
        f.read_from(backup)
        assert f.row_count(1) == 1
        assert f.set_bit(1, 11) is True  # latch cleared by restore


def test_read_corrupt_failpoint_quarantines(faultreg):
    with TestFragment() as f:
        f.set_bit(1, 1)
        f.unload()
        faultreg.configure("fragment.read.corrupt=corrupt:count=1")
        with f.mu:  # fault-in reads the (mutilated) file
            pass
        assert os.path.exists(f.path + ".corrupt")
        assert f.count() == 0


def test_holder_boot_survives_partial_index_failure(faultreg):
    with TestHolder() as h:
        h.create_index("aaa")
        h.create_index("bbb")
        path = h.path
        h.close()
        faultreg.configure("holder.open.partial=error(EIO):count=1")
        from pilosa_tpu.storage.holder import Holder

        h2 = Holder(path)
        h2.open()  # first index (sorted: aaa) fails, boot continues
        try:
            assert sorted(h2.indexes) == ["bbb"]
        finally:
            h2.close()


# --------------------------------------------------- cluster fan-out

def _setup_two_slices(host):
    _post(host, "/index/i", b"{}")
    _post(host, "/index/i/frame/f", b"{}")
    q = (f'SetBit(frame="f", rowID=1, columnID=3)\n'
         f'SetBit(frame="f", rowID=1, columnID={SLICE_WIDTH + 5})')
    _post(host, "/index/i/query", q.encode())


def test_fanout_faults_degrade_per_failover(faultreg):
    """Injected fan-out error AND corrupt responses against a 2-node
    replica_n=2 cluster: every query still answers (slices remap to
    the local replica), the failpoint counters advance, and /metrics
    exports pilosa_faults_triggered_total. A one-shot syncer fault is
    isolated to its fragment and counted, not fatal to the pass."""
    with ServerCluster(2, replica_n=2) as servers:
        for s in servers:
            # Cold mode: PR 5's cluster warm tiers (response replay +
            # result memos) would serve the repeats WITHOUT fanning
            # out — this test exists to exercise the fan-out fault
            # paths, so it runs with caches off (the kill switch the
            # benchmarks use; it also disables the response cache).
            s.executor._result_memo_off = True
        h0 = servers[0].host
        _setup_two_slices(h0)
        assert _query(h0, "i", 'Count(Bitmap(frame="f", rowID=1))') == [2]

        faultreg.configure("client.fanout.error=error(ECONNRESET)")
        assert _query(h0, "i", 'Count(Bitmap(frame="f", rowID=1))') == [2]
        faultreg.clear("client.fanout.error")

        faultreg.configure("client.fanout.corrupt=corrupt:count=2")
        assert _query(h0, "i", 'Count(Bitmap(frame="f", rowID=1))') == [2]
        faultreg.clear("client.fanout.corrupt")

        assert faultreg.metrics()["triggered_total"] >= 1
        m = urllib.request.urlopen(f"http://{h0}/metrics",
                                   timeout=10).read().decode()
        assert "pilosa_faults_triggered_total" in m

        # Diverge node1 locally, then sync with an injected block-fetch
        # fault: the pass survives, the failure is counted.
        servers[1].holder.index("i").frame("f").set_bit(
            "standard", 9, 0, None)
        faultreg.configure("syncer.blocks.error=error(EIO):count=1")
        servers[0].syncer.sync_holder()
        assert servers[0].syncer.errors_total >= 1
        # Next pass (fault exhausted) converges the divergent bit.
        servers[0].syncer.sync_holder()
        assert _query(h0, "i", 'Count(Bitmap(frame="f", rowID=9))') == [1]


def test_fanout_slow_expires_deadline_504(faultreg):
    """client.fanout.slow + a request deadline: the remote leg burns
    the budget, the re-stamped deadline expires on the peer, and the
    coordinator surfaces 504 — the QoS deadline semantics, exercised
    by injection instead of luck."""
    with ServerCluster(2, replica_n=1,
                       qos={"enabled": True}) as servers:
        for s in servers:
            # Cold mode: a warm memo/replay would answer the repeat
            # without the remote leg this test injects delay into.
            s.executor._result_memo_off = True
        h0 = servers[0].host
        _post(h0, "/index/i", b"{}")
        _post(h0, "/index/i/frame/f", b"{}")
        # Find a slice owned by the REMOTE node so the query must fan
        # out (replica_n=1: no failover possible).
        remote_slice = next(
            s for s in range(16)
            if servers[0].cluster.fragment_nodes("i", s)[0].host
            != servers[0].host)
        _post(h0, "/index/i/query",
              f'SetBit(frame="f", rowID=1, '
              f'columnID={remote_slice * SLICE_WIDTH + 1})'.encode())
        assert _query(h0, "i", 'Count(Bitmap(frame="f", rowID=1))') == [1]

        faultreg.configure("client.fanout.slow=delay(0.6)")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h0, "/index/i/query?timeout=0.25",
                  b'Count(Bitmap(frame="f", rowID=1))')
        assert ei.value.code == 504


# ------------------------------------------------------------- drain

def test_drain_waits_for_inflight_and_sheds_new(faultreg, tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0",
               drain_timeout=5.0).open()
    try:
        s.executor._force_path = "serial"  # slice loop => delay applies
        h = s.host
        _post(h, "/index/i", b"{}")
        _post(h, "/index/i/frame/f", b"{}")
        _post(h, "/index/i/query",
              b'SetBit(frame="f", rowID=1, columnID=3)')
        faultreg.configure("executor.slice.delay=delay(0.8)")
        results = {}

        def slow():
            t0 = time.monotonic()
            results["r"] = _query(h, "i",
                                  'Count(Bitmap(frame="f", rowID=1))')
            results["t"] = time.monotonic() - t0

        th = threading.Thread(target=slow)
        th.start()
        time.sleep(0.25)               # the slow query is in flight
        closer = threading.Thread(target=s.close)
        closer.start()
        time.sleep(0.15)               # drain has begun
        st = json.loads(urllib.request.urlopen(
            f"http://{h}/status", timeout=5).read())
        assert st["status"]["state"] == "LEAVING"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h, "/index/i/query",
                  b'Count(Bitmap(frame="f", rowID=1))', timeout=5)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        d = json.loads(urllib.request.urlopen(
            f"http://{h}/debug/drain", timeout=5).read())
        assert d["draining"] is True and d["inFlight"] >= 1
        th.join(20)
        closer.join(20)
        # The in-flight query completed (correct result) even though
        # close() was called while it ran.
        assert results["r"] == [1]
        snap = s.stats.snapshot()
        assert snap.get("drain_duration_seconds", 0) > 0.2
        body = s.handler.get_metrics(None, {}, b"", {})[2]
        assert b"pilosa_drain_duration_seconds" in body
    finally:
        s.close()


def test_debug_faults_endpoint_gated(faultreg, tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    try:
        h = s.host
        out = json.loads(_post(
            h, "/debug/faults",
            json.dumps({"spec": "client.fanout.slow=delay(0)"})
            .encode()).read())
        assert "client.fanout.slow" in out["points"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h, "/debug/faults", b'{"spec": "not a spec"}')
        assert ei.value.code == 400
        out = json.loads(_post(h, "/debug/faults",
                               b'{"clear": true}').read())
        assert out["points"] == {}
        # Gate: with injection disabled the mutation endpoint is 403.
        faults.disable()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(h, "/debug/faults", b'{"spec": "a.b=corrupt"}')
        assert ei.value.code == 403
        out = json.loads(urllib.request.urlopen(
            f"http://{h}/debug/faults", timeout=5).read())
        assert out == {"enabled": False}
    finally:
        s.close()


# ----------------------------------------------- SIGTERM / kill-mid-drain

def _spawn_cli_server(data_dir, port, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env.setdefault("PILOSA_DRAIN_TIMEOUT", "2")
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server", "-d",
         data_dir, "--bind", f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=5).read()
            return proc
        except Exception:  # noqa: BLE001 — still booting
            if proc.poll() is not None:
                raise AssertionError("server died during boot")
            time.sleep(0.25)
    proc.kill()
    raise AssertionError("server did not come up")


def _acknowledged_writes(port, n=50):
    body = "\n".join(
        f'SetBit(frame="f", rowID=1, columnID={c})' for c in range(n))
    _post(f"127.0.0.1:{port}", "/index/i", b"{}")
    _post(f"127.0.0.1:{port}", "/index/i/frame/f", b"{}")
    _post(f"127.0.0.1:{port}", "/index/i/query", body.encode())


def _crash_soak_invariant(data_dir, n=50):
    """Reopen the data dir and assert every ACKNOWLEDGED write is
    present and the fragment file parses — the crash-soak contract."""
    from pilosa_tpu.storage.holder import Holder

    h = Holder(data_dir)
    h.open()
    try:
        frag = h.fragment("i", "f", "standard", 0)
        assert frag is not None
        assert frag.row_count(1) == n
    finally:
        h.close()


def test_sigterm_drains_and_exits_clean(tmp_path):
    from pilosa_tpu.testing import free_ports

    port = free_ports(1)[0]
    data = str(tmp_path / "d1")
    proc = _spawn_cli_server(data, port)
    try:
        _acknowledged_writes(port)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0   # graceful: drained + closed
    finally:
        if proc.poll() is None:
            proc.kill()
    _crash_soak_invariant(data)


def test_kill_during_drain_keeps_crash_invariant(tmp_path):
    from pilosa_tpu.testing import free_ports

    port = free_ports(1)[0]
    data = str(tmp_path / "d2")
    proc = _spawn_cli_server(data, port)
    try:
        _acknowledged_writes(port)
        proc.send_signal(signal.SIGTERM)   # drain begins...
        time.sleep(0.05)
        proc.kill()                        # ...and dies mid-drain
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    _crash_soak_invariant(data)


# --------------------------------------------------------- satellites

def test_hints_bounded_drop_oldest(tmp_path):
    from pilosa_tpu.cluster.cluster import Node
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0")
    ex = s.executor
    cap = ex.HINTS_MAX_PER_PEER
    try:
        ex.HINTS_MAX_PER_PEER = 5
        node = Node("peer:1")
        for i in range(8):
            ex._hint(node, "i", f"call-{i}")
        q = ex._hints["peer:1"]
        assert len(q) == 5
        assert [c for _, c in q] == [f"call-{i}" for i in range(3, 8)]
        assert ex._hints_dropped == 3
        assert s.holder.stats.snapshot()["hints_dropped_total"] == 3
    finally:
        ex.HINTS_MAX_PER_PEER = cap


def test_monitor_errors_logged_and_counted(tmp_path, caplog):
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0")

    def boom():
        raise RuntimeError("kaboom")

    with caplog.at_level("WARNING", logger="pilosa_tpu.server"):
        s._spawn(boom, 0.01)
        deadline = time.monotonic() + 5
        key = "monitor_errors_total;monitor:boom"
        while time.monotonic() < deadline:
            if s.stats.snapshot().get(key, 0) >= 2:
                break
            time.sleep(0.02)
        s._closing.set()
    assert s.stats.snapshot()[key] >= 2   # keeps running after a crash
    assert any("boom" in r.message for r in caplog.records)


def test_backup_restore_checksum_verification(tmp_path):
    from pilosa_tpu.cli.__main__ import main as cli_main
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    try:
        _post(s.host, "/index/i", b"{}")
        _post(s.host, "/index/i/frame/f", b"{}")
        _post(s.host, "/index/i/query",
              b'SetBit(frame="f", rowID=1, columnID=3)\n'
              b'SetBit(frame="f", rowID=2, columnID=4)')
        tar_path = str(tmp_path / "b.tar")
        assert cli_main(["backup", "--host", s.host, "-i", "i", "-f", "f",
                         "-o", tar_path]) == 0
        with tarfile.open(tar_path) as tar:
            names = tar.getnames()
        assert "0" in names and "0.checksum" in names

        # Clean restore into a fresh frame verifies and succeeds.
        assert cli_main(["restore", "--host", s.host, "-i", "j", "-f", "f",
                        tar_path]) == 0
        assert _query(s.host, "j",
                      'Count(Bitmap(frame="f", rowID=1))') == [1]

        # Tamper with the recorded checksum: restore fails LOUDLY.
        bad_path = str(tmp_path / "bad.tar")
        with tarfile.open(tar_path) as src, \
                tarfile.open(bad_path, "w") as dst:
            for member in src.getmembers():
                data = src.extractfile(member).read()
                if member.name == "0.checksum":
                    data = b"0" * 16
                info = tarfile.TarInfo(member.name)
                info.size = len(data)
                dst.addfile(info, io.BytesIO(data))
        assert cli_main(["restore", "--host", s.host, "-i", "k", "-f", "f",
                        bad_path]) == 1
    finally:
        s.close()


def test_failstop_maps_to_http_503(faultreg, tmp_path):
    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "data"), bind="localhost:0").open()
    try:
        _post(s.host, "/index/i", b"{}")
        _post(s.host, "/index/i/frame/f", b"{}")
        _post(s.host, "/index/i/query",
              b'SetBit(frame="f", rowID=1, columnID=3)')
        faultreg.configure("fragment.append.fsync=error(ENOSPC):count=1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(s.host, "/index/i/query",
                  b'SetBit(frame="f", rowID=1, columnID=4)')
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        # Reads on the fail-stopped fragment still serve.
        assert _query(s.host, "i",
                      'Count(Bitmap(frame="f", rowID=1))') == [1]
        # /metrics exports the fail-stop counter.
        m = urllib.request.urlopen(f"http://{s.host}/metrics",
                                   timeout=10).read().decode()
        assert "pilosa_fragment_failstop_total" in m
    finally:
        s.close()
