"""Distributed query tracing (pilosa_tpu/tracing.py) + the
observability satellites: span nesting, ring eviction, header
propagation through Handler.dispatch and across a real 2-node
cluster, the slow-query flight recorder on /metrics, prometheus
exposition edge cases, statsd client-side sampling, and the py3.10
config (tomllib fallback) regression."""
import io
import json
import urllib.request

import pytest

from pilosa_tpu import SLICE_WIDTH, tracing
from pilosa_tpu.server.server import Server
from pilosa_tpu.testing import free_ports


def http(method, url, body=None, ctype="application/json", headers=None):
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", ctype)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def jget(url):
    status, data, _ = http("GET", url)
    assert status == 200, data
    return json.loads(data)


def base(s):
    return f"http://{s.host}"


# ----------------------------------------------------------- unit: tracer


def test_span_nesting_and_tree():
    tr = tracing.Tracer(ring_size=8)
    with tr.start("query", index="i"):
        with tracing.span("parse"):
            pass
        with tracing.span("call:Count"):
            with tracing.span("slice", slice=0):
                pass
            with tracing.span("slice", slice=1):
                pass
    assert tracing.active_span() is None
    d = tr.recent(1)[0]
    assert {s["name"] for s in d["spans"]} == {
        "query", "parse", "call:Count", "slice"}
    (root,) = d["roots"]
    assert root["name"] == "query"
    kids = [c["name"] for c in root["children"]]
    assert kids == ["parse", "call:Count"]
    count_node = root["children"][1]
    assert [c["tags"]["slice"] for c in count_node["children"]] == [0, 1]
    assert all(s["durationMs"] is not None for s in d["spans"])


def test_ring_eviction():
    tr = tracing.Tracer(ring_size=4)
    for i in range(10):
        with tr.start("q", n=i):
            pass
    assert tr.ring_len() == 4
    got = [t["roots"][0]["tags"]["n"] for t in tr.recent(10)]
    assert got == [9, 8, 7, 6]  # newest first, oldest evicted


def test_slow_ring_and_stats():
    from pilosa_tpu.stats import ExpvarStatsClient, prometheus_exposition

    stats = ExpvarStatsClient()
    tr = tracing.Tracer(ring_size=8, slow_threshold=0.0, stats=stats)
    with tr.start("q"):
        pass
    assert tr.ring_len(slow=True) == 1
    snap = stats.snapshot()
    assert snap["slow_queries_total"] == 1
    assert snap["query_latency_seconds_count"] == 1
    expo = prometheus_exposition(snap)
    assert "pilosa_slow_queries_total 1" in expo
    assert 'pilosa_query_latency_seconds_bucket{le="5.0"} 1' in expo
    # Prometheus histogram_quantile() needs an explicit +Inf bucket.
    assert 'pilosa_query_latency_seconds_bucket{le="+Inf"} 1' in expo


def test_nop_paths_record_nothing():
    # Module-level span() with no active trace is the shared nop CM.
    assert tracing.span("anything", x=1) is tracing.NOP_SPAN
    assert tracing.child_of(None, "x") is tracing.NOP_SPAN
    assert tracing.trace_headers() is None
    with tracing.NOP_SPAN as sp:
        sp.tag(a=1)  # must not blow up
    nop = tracing.NopTracer()
    with nop.start("q"):
        pass
    assert nop.recent() == [] and nop.ring_len() == 0


def test_stitch_merges_cross_node_spans():
    tr_a, tr_b = tracing.Tracer(), tracing.Tracer()
    with tr_a.start("query") as root:
        with tracing.span("node.remote", host="b") as fan:
            fan_id = fan.span_id
        tid = root.trace.trace_id
    # The "remote" node adopts the propagated ids.
    with tr_b.start("query.remote", trace_id=tid, parent_id=fan_id):
        with tracing.span("slice", slice=3):
            pass
    stitched = tracing.stitch(tr_a.recent(1) + tr_b.recent(1))
    assert stitched["traceId"] == tid
    (root_node,) = stitched["roots"]
    fan_node = next(c for c in root_node["children"]
                    if c["name"] == "node.remote")
    assert fan_node["children"][0]["name"] == "query.remote"
    with pytest.raises(ValueError):
        tracing.stitch(tr_a.recent(1)
                       + [{"traceId": "other", "spans": []}])


# ------------------------------------------ handler round trip (1 node)


@pytest.fixture
def traced_server(tmp_path):
    s = Server(str(tmp_path / "data"), bind="localhost:0",
               trace_enabled=True, trace_slow_threshold=0.0).open()
    yield s
    s.close()


def _seed(s, slices=2):
    b = base(s)
    http("POST", f"{b}/index/i", b"{}")
    http("POST", f"{b}/index/i/frame/f", b"{}")
    for sl in range(slices):
        http("POST", f"{b}/index/i/query",
             f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + 1})'
             .encode())


def test_header_adoption_through_dispatch(traced_server):
    """A query arriving with propagated trace headers records its
    trace under the REMOTE ids — the round trip the coordinator's
    fan-out performs, exercised through Handler.dispatch."""
    h = traced_server.handler
    _seed(traced_server)
    status, _, payload = h.dispatch(
        "POST", "/index/i/query", {},
        b'Count(Bitmap(frame="f", rowID=1))',
        {"X-Pilosa-Trace-Id": "feedbeeffeedbeef",
         "X-Pilosa-Span-Id": "cafecafecafecafe"})[:3]
    assert status == 200, payload
    traces = h.tracer.recent(5, trace_id="feedbeeffeedbeef")
    assert traces, "remote trace id was not adopted"
    d = traces[0]
    roots = d["roots"]
    assert roots[0]["name"] == "query.remote"
    assert roots[0]["parentId"] == "cafecafecafecafe"
    names = {s["name"] for s in d["spans"]}
    assert "parse" in names and "call:Count" in names


def test_profile_inline_and_response_header(traced_server):
    _seed(traced_server)
    status, data, hdrs = http(
        "POST", f"{base(traced_server)}/index/i/query?profile=true",
        b'Count(Bitmap(frame="f", rowID=1))')
    assert status == 200
    doc = json.loads(data)
    assert doc["results"] == [2]
    prof = doc["profile"]
    assert prof["traceId"] == hdrs["X-Pilosa-Trace-Id"]
    assert prof["roots"][0]["name"] == "query"
    assert any(s["name"] == "parse" for s in prof["spans"])


def test_profile_without_global_tracing(tmp_path):
    """?profile=true on a tracing-disabled server: ephemeral recorder,
    span tree in the response, nothing retained server-side."""
    s = Server(str(tmp_path / "d"), bind="localhost:0").open()
    try:
        _seed(s)
        status, data, _ = http(
            "POST", f"{base(s)}/index/i/query?profile=true",
            b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200
        assert json.loads(data)["profile"]["roots"]
        assert s.handler.tracer is tracing.NOP
        out = jget(f"{base(s)}/debug/traces")
        assert out == {"enabled": False, "slowThresholdMs": 250.0,
                       "summary": {}, "traces": []}
    finally:
        s.close()


def test_debug_traces_and_slow_metrics(traced_server):
    _seed(traced_server)
    b = base(traced_server)
    status, data, _ = http("POST", f"{b}/index/i/query",
                           b'Count(Bitmap(frame="f", rowID=1))')
    assert status == 200
    out = jget(f"{b}/debug/traces")
    assert out["enabled"] and out["traces"]
    # slow-threshold 0 ⇒ every query is slow: flight recorder + metric.
    slow = jget(f"{b}/debug/traces?slow=true")
    assert slow["traces"]
    _, expo, _ = http("GET", f"{b}/metrics")
    assert b"pilosa_slow_queries_total" in expo
    assert b"pilosa_query_latency_seconds_bucket" in expo


def test_diagnostics_flush_includes_perf_summary(traced_server, tmp_path):
    from pilosa_tpu.diagnostics import Diagnostics

    _seed(traced_server)
    http("POST", f"{base(traced_server)}/index/i/query",
         b'Count(Bitmap(frame="f", rowID=1))')
    sink = tmp_path / "diag.jsonl"
    d = Diagnostics(server=traced_server, sink_path=str(sink))
    rec = d.flush()
    assert rec["SlowQueries"] >= 1
    assert rec["TracingSummary"]["slowQueries"] >= 1
    assert "QueryLatencyP50Ms" in rec
    assert json.loads(sink.read_text().splitlines()[0]) == rec


# --------------------------------------------- distributed stitch (2 nodes)


def test_distributed_fanout_trace_stitches(tmp_path):
    """Acceptance: a fan-out query with tracing enabled yields ONE
    trace tree — coordinator + remote spans stitched by the propagated
    trace id — with per-slice spans >= the slice count; the same query
    with tracing disabled takes the nop path (no ring growth)."""
    ports = free_ports(2)
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        Server(str(tmp_path / f"n{i}"), bind=hosts[i], cluster_hosts=hosts,
               replica_n=1, anti_entropy_interval=0, polling_interval=0,
               trace_enabled=True, trace_slow_threshold=30.0).open()
        for i in range(2)
    ]
    try:
        a, b = servers
        for s in servers:
            # Pin the serial per-slice path so every slice gets a span
            # (the batched path runs one fused program per node).
            s.executor._force_path = "serial"
        http("POST", f"{base(a)}/index/i", b"{}")
        http("POST", f"{base(a)}/index/i/frame/f", b"{}")
        n_slices = 6
        for sl in range(n_slices):
            status, data, _ = http(
                "POST", f"{base(a)}/index/i/query",
                f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + 1})'
                .encode())
            assert status == 200, data

        status, data, hdrs = http("POST", f"{base(a)}/index/i/query",
                                  b'Count(Bitmap(frame="f", rowID=1))')
        assert status == 200 and json.loads(data)["results"] == [n_slices]
        tid = hdrs["X-Pilosa-Trace-Id"]

        # Gather the trace's pieces from EACH node's ring and stitch.
        pieces = []
        for s in servers:
            out = jget(f"{base(s)}/debug/traces?traceId={tid}")
            pieces.extend(out["traces"])
        assert len(pieces) >= 2, "remote node recorded no adopted trace"
        stitched = tracing.stitch(pieces)
        assert stitched["traceId"] == tid
        (root,) = stitched["roots"]  # ONE tree: remote roots resolved
        assert root["name"] == "query"

        names = [s["name"] for s in stitched["spans"]]
        assert names.count("slice") >= n_slices
        assert "node.remote" in names and "node.local" in names
        assert "remote.round" in names
        assert any(n == "query.remote" for n in names)

        # Remote spans sit UNDER the coordinator's fan-out span.
        def find(node, name):
            if node["name"] == name:
                return node
            for c in node["children"]:
                hit = find(c, name)
                if hit is not None:
                    return hit
            return None

        fan = find(root, "node.remote")
        assert fan is not None and find(fan, "query.remote") is not None

        # Tracing disabled ⇒ nop path, no ring growth.
        ports2 = free_ports(2)
        hosts2 = [f"localhost:{p}" for p in ports2]
        plain = [
            Server(str(tmp_path / f"p{i}"), bind=hosts2[i],
                   cluster_hosts=hosts2, replica_n=1,
                   anti_entropy_interval=0, polling_interval=0).open()
            for i in range(2)
        ]
        try:
            http("POST", f"{base(plain[0])}/index/i", b"{}")
            http("POST", f"{base(plain[0])}/index/i/frame/f", b"{}")
            for sl in range(n_slices):
                http("POST", f"{base(plain[0])}/index/i/query",
                     f'SetBit(frame="f", rowID=1, columnID='
                     f'{sl * SLICE_WIDTH + 1})'.encode())
            status, data, hdrs = http(
                "POST", f"{base(plain[0])}/index/i/query",
                b'Count(Bitmap(frame="f", rowID=1))')
            assert status == 200 and json.loads(data)["results"] == [n_slices]
            assert "X-Pilosa-Trace-Id" not in hdrs
            for s in plain:
                assert s.handler.tracer is tracing.NOP
                assert s.handler.tracer.ring_len() == 0
                assert jget(f"{base(s)}/debug/traces")["traces"] == []
        finally:
            for s in plain:
                s.close()
    finally:
        for s in servers:
            s.close()


# ---------------------------------------------------- exposition edge cases


def test_prometheus_exposition_edge_cases():
    from pilosa_tpu.stats import prometheus_exposition

    snap = {
        "Plain": 3,
        "Quoted;who:say \"hi\"": 1,
        "Newline;msg:a\nb": 2,
        "Comma;list:a,b": 4,       # comma splits the tag list: must
        "BoolSkipped": True,       # still render a parseable line
        "StrSkipped": "nope",
        "Float": 1.5,
    }
    out = prometheus_exposition(
        snap, namespaced=(("grp", {"x": 7, "skip": False}),))
    lines = out.strip().splitlines()
    assert "pilosa_Plain 3" in lines
    assert 'pilosa_Quoted{who="say \\"hi\\""} 1' in lines
    assert 'pilosa_Newline{msg="a\\nb"} 2' in lines
    assert "pilosa_grp_x 7" in lines
    assert not any("BoolSkipped" in ln or "StrSkipped" in ln
                   or "grp_skip" in ln for ln in lines)
    comma = next(ln for ln in lines if ln.startswith("pilosa_Comma"))
    # Exposition-format sanity for the degraded comma case: every label
    # is key="value" and the sample value survives.
    import re

    m = re.fullmatch(r'pilosa_Comma\{([^}]*)\} 4', comma)
    assert m, comma
    for label in m.group(1).split(","):
        assert re.fullmatch(r'\w*="[^"]*"', label), label


def test_statsd_rate_sampling_deterministic():
    from pilosa_tpu.stats import StatsdClient

    sent = []

    class _Sock:
        def sendto(self, payload, addr):
            sent.append(payload.decode())

    rolls = iter([0.05, 0.95, 0.05, 0.95])
    c = StatsdClient(_sock=_Sock(), _rand=lambda: next(rolls))
    c.count("hits", 1, rate=0.1)   # 0.05 < 0.1 → sent
    c.count("hits", 1, rate=0.1)   # 0.95 ≥ 0.1 → dropped
    c.timing("lat", 0.5, rate=0.5)  # 0.05 < 0.5 → sent
    c.gauge("g", 2, rate=0.5)       # 0.95 ≥ 0.5 → dropped
    assert sent == ["hits:1|c|@0.1", "lat:500|ms|@0.5"]
    c.count("always", 1)            # rate=1.0 never consults _rand
    assert sent[-1] == "always:1|c"
    # with_tags children inherit the seam (and the socket).
    rolls2 = iter([0.01])
    c2 = StatsdClient(_sock=_Sock(), _rand=lambda: next(rolls2))
    c2.with_tags("k:v").count("tagged", 1, rate=0.9)
    assert sent[-1] == "tagged:1|c|@0.9|#k:v"


# ------------------------------------------------- config py3.10 regression


def test_config_imports_and_loads_on_this_interpreter(tmp_path):
    """Regression for the py3.10 tomllib break: the module must import
    and parse TOML on whatever interpreter runs the suite."""
    import pilosa_tpu.config as cfgmod

    p = tmp_path / "c.toml"
    p.write_text('bind = "localhost:7777"\n\n[trace]\n  enabled = true\n'
                 '  slow-threshold = 0.5\n')
    cfg = cfgmod.Config.load(str(p), env={})
    assert cfg.bind == "localhost:7777"
    assert cfg.trace["enabled"] is True
    assert cfg.trace["slow-threshold"] == 0.5
    # The generated config round-trips through the same reader.
    p2 = tmp_path / "rt.toml"
    p2.write_text(cfg.to_toml())
    rt = cfgmod.Config.load(str(p2), env={})
    assert rt.trace == cfg.trace


def test_minitoml_fallback_parses_config_subset():
    """The vendored last-resort reader handles everything
    Config.to_toml emits, with the tomllib API shape."""
    from pilosa_tpu.config import Config
    from pilosa_tpu.utils import minitoml

    text = Config().to_toml()
    data = minitoml.load(io.BytesIO(text.encode()))
    assert data["bind"] == Config().bind
    assert data["cluster"]["replicas"] == 1
    assert data["cluster"]["hosts"] == [Config().bind]
    assert data["trace"]["enabled"] is False
    assert data["trace"]["slow-threshold"] == 0.25
    # Inline comments after values — including after a closed string,
    # the docs/configuration.md example shape — must strip.
    inline = minitoml.loads('host = "127.0.0.1:8125"  # statsd target\n'
                            'n = 3  # count\n'
                            'frag = "has # inside"\n'
                            '[trace]  # table-header comment\n'
                            'enabled = true\n')
    assert inline == {"host": "127.0.0.1:8125", "n": 3,
                      "frag": "has # inside",
                      "trace": {"enabled": True}}
    with pytest.raises(minitoml.TOMLDecodeError):
        minitoml.loads("key value-without-equals")
