"""Pallas kernel parity vs NumPy, run in interpreter mode on the CPU
test mesh (on a real TPU the same code compiles via Mosaic)."""
import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk

pytestmark = pytest.mark.skipif(not pk._HAVE_PALLAS,
                                reason="pallas unavailable")


def _rand(shape, seed):
    return np.random.default_rng(seed).integers(
        0, 1 << 32, size=shape, dtype=np.uint64).astype(np.uint32)


def test_count_and_matches_numpy():
    a = _rand((8, 512), 0)
    b = _rand((8, 512), 1)
    want = int(np.bitwise_count(a & b).sum())
    assert int(pk.count_and(a, b)) == want


def test_count_and_1d():
    a = _rand((256,), 2)
    b = _rand((256,), 3)
    want = int(np.bitwise_count(a & b).sum())
    assert int(pk.count_and(a, b)) == want


def test_count_rows_matches_numpy():
    m = _rand((16, 384), 4)
    want = np.bitwise_count(m).sum(axis=1)
    got = np.asarray(pk.count_rows(m))
    assert (got == want).all()


def test_count_and_rows_matches_numpy():
    m = _rand((12, 256), 5)
    f = _rand((256,), 6)
    want = np.bitwise_count(m & f).sum(axis=1)
    got = np.asarray(pk.count_and_rows(m, f))
    assert (got == want).all()


def test_non_lane_multiple_width_padded():
    # widths not a multiple of 128 are zero-padded by the wrappers
    m = _rand((8, 192), 7)
    f = _rand((192,), 8)
    assert int(pk.count_and(m, m)) == int(np.bitwise_count(m).sum())
    got = np.asarray(pk.count_and_rows(m, f))
    assert (got == np.bitwise_count(m & f).sum(axis=1)).all()
    got = np.asarray(pk.count_rows(m))
    assert (got == np.bitwise_count(m).sum(axis=1)).all()


def test_non_sublane_multiple_rows_padded():
    # row counts not a multiple of 8 are zero-padded and trimmed
    m = _rand((12, 256), 9)
    f = _rand((256,), 10)
    assert int(pk.count_and(m, m)) == int(np.bitwise_count(m).sum())
    got = np.asarray(pk.count_and_rows(m, f))
    assert got.shape == (12,)
    assert (got == np.bitwise_count(m & f).sum(axis=1)).all()
