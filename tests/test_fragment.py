"""Fragment tests — modeled on the reference's fragment_test.go suite:
set/clear, persistence (reopen), snapshot, import, BSI field ops, TopN,
blocks/checksums, merge, backup round-trip."""
import io
import os

import numpy as np
import pytest

from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.storage import fragment as frag_mod
from pilosa_tpu.storage.fragment import WORDS64, Fragment, TopOptions


@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    yield f
    f.close()


def test_set_clear_bit(frag):
    assert frag.set_bit(10, 3) is True
    assert frag.set_bit(10, 3) is False       # already set
    assert frag.row_count(10) == 1
    assert frag.clear_bit(10, 3) is True
    assert frag.clear_bit(10, 3) is False
    assert frag.row_count(10) == 0


def test_slice_bounds(tmp_path):
    f = Fragment(str(tmp_path / "s2"), "i", "f", "standard", 2).open()
    f.set_bit(0, 2 * SLICE_WIDTH + 5)
    assert f.row_count(0) == 1
    with pytest.raises(ValueError):
        f.set_bit(0, 5)  # column belongs to slice 0
    f.close()


def test_persistence_reopen(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    bits = [(0, 1), (0, 2), (5, 100), (120, SLICE_WIDTH - 1)]
    for r, c in bits:
        f.set_bit(r, c)
    f.clear_bit(0, 2)
    f.close()

    f2 = Fragment(path, "i", "f", "standard", 0).open()
    assert f2.row_count(0) == 1
    assert f2.row_count(5) == 1
    assert f2.row_count(120) == 1
    assert f2.op_n == 5  # op log replayed, no snapshot yet
    f2.close()


def test_snapshot_resets_oplog(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    for c in range(10):
        f.set_bit(1, c)
    f.snapshot()
    assert f.op_n == 0
    f.set_bit(1, 100)
    f.close()
    f2 = Fragment(path, "i", "f", "standard", 0).open()
    assert f2.row_count(1) == 11
    # The count above served lazily (no fault-in) — op_n still comes
    # from the lazy reader's op-log parse.
    assert f2.op_n == 1
    assert not f2._resident
    f2.close()


def test_auto_snapshot_at_max_opn(tmp_path, monkeypatch):
    monkeypatch.setattr(frag_mod, "MAX_OPN", 50)
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    for c in range(60):
        f.set_bit(0, c)
    assert f.op_n <= 50
    assert f.row_count(0) == 60
    f.close()


def test_import_bits(frag):
    rows = [0, 0, 0, 3, 3, 7]
    cols = [1, 5, 9, 2, 2, SLICE_WIDTH - 1]
    frag.import_bits(rows, cols)
    assert frag.row_count(0) == 3
    assert frag.row_count(3) == 1    # duplicate collapsed
    assert frag.row_count(7) == 1
    assert frag.op_n == 6            # small batch: op-log append path


def test_row_words_and_device(frag):
    frag.set_bit(2, 65)
    w = frag.row_words(2)
    assert w[1] == np.uint64(2)      # bit 65 = word 1, bit 1
    dev = np.asarray(frag.device_row(2))
    assert dev[2] == 2               # uint32 word 2, bit 1


def test_count(frag):
    frag.import_bits([0, 1, 2], [0, 0, 0])
    frag.set_bit(0, 9)
    assert frag.count() == 4


def test_bsi_field_ops(frag):
    depth = 8
    vals = {3: 17, 9: 200, 100: 0, 5000: 255}
    for col, v in vals.items():
        frag.set_field_value(col, depth, v)
    for col, v in vals.items():
        got, exists = frag.field_value(col, depth)
        assert exists and got == v
    assert frag.field_value(12345, depth) == (0, False)

    total, count = frag.field_sum(None, depth)
    assert total == sum(vals.values()) and count == len(vals)

    # filter to a subset of columns
    filt = np.zeros(frag_mod.WORDS64, dtype=np.uint64)
    for col in (3, 9):
        filt[col >> 6] |= np.uint64(1 << (col & 63))
    total, count = frag.field_sum(filt, depth)
    assert total == 217 and count == 2

    def cols_of(words):
        return set(np.flatnonzero(
            np.unpackbits(words.view(np.uint8), bitorder="little")).tolist())

    assert cols_of(frag.field_range("<", depth, 200)) == {3, 100}
    assert cols_of(frag.field_range("<=", depth, 200)) == {3, 9, 100}
    assert cols_of(frag.field_range("==", depth, 200)) == {9}
    assert cols_of(frag.field_range("!=", depth, 200)) == {3, 100, 5000}
    assert cols_of(frag.field_range(">", depth, 17)) == {9, 5000}
    assert cols_of(frag.field_range_between(depth, 17, 200)) == {3, 9}
    assert cols_of(frag.field_not_null(depth)) == set(vals)

    assert frag.field_min_max(None, depth, True) == (255, 1)
    assert frag.field_min_max(None, depth, False) == (0, 1)


def test_topn(frag):
    frag.import_bits(
        [0] * 5 + [1] * 10 + [2] * 3 + [3] * 10,
        list(range(5)) + list(range(10)) + list(range(3)) + list(range(100, 110)))
    top = frag.top(TopOptions(n=2))
    assert top == [(1, 10), (3, 10)]  # ties broken by ascending row id
    assert frag.top(TopOptions()) == [(1, 10), (3, 10), (0, 5), (2, 3)]

    # src-restricted counts
    src = np.zeros(frag_mod.WORDS64, dtype=np.uint64)
    src[0] = np.uint64(0b111)  # columns 0..2
    top = frag.top(TopOptions(n=2, src=src))
    assert top == [(0, 3), (1, 3)]

    # explicit candidate restriction
    assert frag.top(TopOptions(row_ids=[2, 3])) == [(3, 10), (2, 3)]


def test_topn_tanimoto(frag):
    frag.import_bits([0] * 4 + [1] * 4, [0, 1, 2, 3, 0, 1, 10, 11])
    src = np.zeros(frag_mod.WORDS64, dtype=np.uint64)
    src[0] = np.uint64(0b1111)  # cols 0-3; row0 tanimoto=100, row1=2/6=33
    top = frag.top(TopOptions(src=src, tanimoto_threshold=50))
    assert top == [(0, 4)]


def test_blocks_checksums(frag):
    assert frag.blocks() == []
    frag.set_bit(0, 1)
    b1 = frag.blocks()
    assert [b for b, _ in b1] == [0]
    frag.set_bit(250, 1)  # block 2
    b2 = frag.blocks()
    assert [b for b, _ in b2] == [0, 2]
    assert b2[0][1] == b1[0][1]  # block 0 unchanged
    frag.set_bit(0, 2)
    assert frag.blocks()[0][1] != b1[0][1]
    assert frag.block_data(2)[0].tolist() == [250]


def test_merge_block(frag):
    # local has (0,1); remote has (0,2). 2 participants, majority=1 -> union.
    frag.set_bit(0, 1)
    diffs = frag.merge_block(0, [([0], [2])])
    assert frag.row_count(0) == 2          # local gained (0,2)
    assert diffs == [([(0, 1)], [])]        # remote needs (0,1) set

    # 3 participants, majority=2: minority bits get cleared everywhere.
    # local={(0,1),(0,2)}, r1={(0,1)}, r2={(0,9)} -> consensus={(0,1)}.
    diffs = frag.merge_block(0, [([0], [1]), ([0], [9])])
    assert frag.row_count(0) == 1           # (0,2) lost its majority
    assert diffs[0] == ([], [])             # replica 1 already at consensus
    assert diffs[1][0] == [(0, 1)]          # replica 2 must set (0,1)
    assert diffs[1][1] == [(0, 9)]          # ... and clear (0,9)


def test_backup_roundtrip(tmp_path):
    f = Fragment(str(tmp_path / "a"), "i", "f", "standard", 0).open()
    f.import_bits([0, 1, 9], [5, 6, 7])
    buf = io.BytesIO()
    f.write_to(buf)
    f.close()

    g = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0).open()
    buf.seek(0)
    g.read_from(buf)
    assert g.count() == 3
    assert g.row_count(9) == 1
    g.close()
    # restored file persists
    h = Fragment(str(tmp_path / "b"), "i", "f", "standard", 0).open()
    assert h.count() == 3
    h.close()


def test_torn_oplog_recovery(tmp_path):
    """A partial trailing op record (crash mid-append) must not brick the
    fragment: open recovers the valid prefix and rewrites the file."""
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.set_bit(0, 1)
    f.set_bit(0, 2)
    f.close()
    with open(path, "ab") as fh:
        fh.write(b"\x00\x07\x00")  # torn record
    f2 = Fragment(path, "i", "f", "standard", 0).open()
    # Lazy read: the valid op prefix applies, the torn tail is ignored
    # in place (every reader sees the same consistent prefix).
    assert f2.row_count(0) == 2
    assert f2.op_n == 2 and not f2._resident
    # The first WRITE faults in, which detects the torn tail and
    # rewrites the file via snapshot before appending the new op.
    f2.set_bit(0, 3)
    assert f2.op_n == 1  # clean rewrite + the one new op
    f2.close()
    f3 = Fragment(path, "i", "f", "standard", 0).open()
    assert f3.row_count(0) == 3
    f3.close()


def test_narrow_width_grows_and_persists(tmp_path):
    """Rows allocate words only up to the widest touched column
    (powers of two from 64): narrow shapes stay narrow across reopen,
    width grows transparently, and full-width APIs pad."""
    from pilosa_tpu.storage.fragment import WORDS64, Fragment

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    f.import_bits([0] * 3 + [1] * 2, [1, 5, 4000, 7, 4095])
    assert f._w64 == 64  # 4096 columns
    assert f.count() == 5
    assert len(f.row_words(0)) == WORDS64  # padded API
    f.close()

    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    assert f2._w64 == 64  # narrow file reopens narrow
    assert f2.count() == 5 and f2.row_count(0) == 3
    # touching a high column grows the width; bits survive
    f2.set_bit(0, 1048575)
    assert f2._w64 == WORDS64
    assert f2.row_count(0) == 4
    f2.close()

    f3 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    assert f3.count() == 6
    f3.close()


def test_narrow_matrix_top_with_wide_src(tmp_path):
    """TopN src bitmaps may be wider than a narrow fragment matrix:
    intersections trim to the matrix width, but the Tanimoto |src|
    denominator counts the FULL src."""
    import numpy as np

    from pilosa_tpu.storage.fragment import WORDS64, Fragment, TopOptions

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    f.import_bits([0, 0, 1], [1, 2, 1])  # narrow rows
    src = np.zeros(WORDS64, dtype=np.uint64)
    src[0] = np.uint64(0b110)       # cols 1,2 (inside width)
    src[WORDS64 - 1] = np.uint64(1)  # one col far beyond width
    # plain src counts: |row ∩ src| ignores the out-of-width src bit
    top = f.top(TopOptions(src=src))
    assert top == [(0, 2), (1, 1)]
    # tanimoto: row0: inter=2, |A|=2, |B|=3 → 100·2/3 = 66.7 → ceil 67
    top = f.top(TopOptions(src=src, tanimoto_threshold=66))
    assert top == [(0, 2)]
    top = f.top(TopOptions(src=src, tanimoto_threshold=67))
    assert top == []
    f.close()


def test_import_value_duplicate_columns_last_wins(frag):
    """Duplicate columns in one batch apply sequentially — last value
    wins (ref: importValue fragment.go:1335 applies pairs in order);
    the vectorized clear-then-set must not OR the values together."""
    frag.import_value_bits([5, 5, 5], [3, 12, 9], 8)
    assert frag.field_value(5, 8) == (9, True)
    frag.import_value_bits([5], [1], 8)
    assert frag.field_value(5, 8) == (1, True)


def test_import_value_bits(frag):
    frag.import_value_bits([1, 2, 3], [10, 20, 30], 8)
    assert frag.field_value(1, 8) == (10, True)
    assert frag.field_value(2, 8) == (20, True)
    # Small FRESH-INSERT BSI imports ride the op log — (depth+2)
    # records per value (null sandwich + planes) — instead of
    # snapshotting per call.
    assert frag.op_n == 10 * 3
    # overwrite clears stale planes
    frag.import_value_bits([1], [255], 8)
    assert frag.field_value(1, 8) == (255, True)
    assert frag.field_sum(None, 8) == (305, 3)
    # Overwrites SNAPSHOT (op log reset): a torn op-log group replays
    # as null, which may only lose unacknowledged writes — column 1's
    # old value was acknowledged, so the old-or-new guarantee of the
    # reference's snapshot + atomic rename applies
    # (fragment.go:1335-1367).
    assert frag.op_n == 0


def test_import_value_overwrite_never_rides_oplog(tmp_path):
    """Any batch touching an existing (not-null) column snapshots, even
    when most of the batch is fresh inserts — the torn-group replay
    (null) may only erase unacknowledged writes, never an acknowledged
    value (ADVICE r3; ref ImportValue old-or-new via snapshot+rename,
    fragment.go:1335-1367)."""
    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0).open()
    f.import_value_bits([100], [7], 8)          # fresh: op log
    assert f.op_n == 10
    f.import_value_bits([200, 100, 300], [1, 2, 3], 8)  # 100 = overwrite
    assert f.op_n == 0                          # snapshotted
    f.import_value_bits([400, 500], [4, 5], 8)  # all fresh again
    assert f.op_n == 20
    f.close()
    f2 = Fragment(p, "i", "f", "standard", 0).open()
    assert f2.field_value(100, 8) == (2, True)
    assert f2.field_value(400, 8) == (4, True)
    f2.close()


def test_cache_sidecar_persistence(tmp_path):
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0, cache_type="ranked").open()
    f.import_bits([1, 1, 2], [0, 1, 0])
    f.close()
    f2 = Fragment(path, "i", "f", "standard", 0, cache_type="ranked").open()
    assert f2.cache.get(1) == 2
    assert f2.cache.get(2) == 1
    f2.close()


def test_small_import_appends_oplog_and_replays(tmp_path):
    """Small bulk imports take the batch op-log append path (no full
    snapshot) and must survive reopen via replay."""
    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0).open()
    f.import_bits([0, 0, 5], [1, 9, 3])
    assert f.op_n == 3  # appended, not snapshotted
    size_after_small = os.path.getsize(p)
    f.close()

    f2 = Fragment(p, "i", "f", "standard", 0).open()
    assert f2.count() == 3
    assert f2.row_count(0) == 2 and f2.row_count(5) == 1
    f2.close()
    assert size_after_small > 0


def test_large_import_snapshots(tmp_path):
    from pilosa_tpu.storage.fragment import MAX_OPN

    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0).open()
    n = MAX_OPN + 10
    f.import_bits([0] * n, list(range(n)))
    assert f.op_n == 0  # snapshot reset
    f.close()
    f2 = Fragment(p, "i", "f", "standard", 0).open()
    assert f2.count() == n
    f2.close()


def test_fragment_file_lock(tmp_path):
    """Double-open of the same fragment file is rejected while the
    first holder lives (ref: syscall.Flock fragment.go:203-205).
    flock is per-(process, fd) so the second opener is a subprocess."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = str(tmp_path / "frag")
    f = Fragment(path, "i", "f", "standard", 0).open()
    f.set_bit(1, 2)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=root + os.pathsep + os.environ.get("PYTHONPATH", ""))
    code = subprocess.run(
        [sys.executable, "-c", f"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize forces axon
from pilosa_tpu import errors as perr
from pilosa_tpu.storage.fragment import Fragment
try:
    Fragment({path!r}, "i", "f", "standard", 0).open()
except perr.ErrFragmentLocked:
    sys.exit(42)
sys.exit(0)
"""],
        env=env, timeout=120,
    ).returncode
    assert code == 42
    f.close()
    # after close the lock is released and the bit survived
    f2 = Fragment(path, "i", "f", "standard", 0).open()
    assert f2.row_count(1) == 1
    f2.close()


def test_high_column_window_stays_narrow(tmp_path):
    """Data clustered in HIGH columns allocates only its cluster's
    window, not the full slice (VERDICT r1: a sparse row touching a
    high column used to allocate full width)."""
    hi = SLICE_WIDTH - 1
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    assert f.set_bit(3, hi)
    assert f.set_bit(3, hi - 100)
    assert f._w64 == 64 and f._w64_base == WORDS64 - 64
    assert f.row_count(3) == 2
    words = f.row_words(3)
    assert words.shape == (WORDS64,)
    assert bool(words[WORDS64 - 1] >> 63 & 1)

    # Device row scatters at the window offset.
    dev = np.asarray(f.device_row(3)).view(np.uint64)
    assert (dev == words).all()

    # Clears outside the window are no-ops and don't grow it.
    assert not f.clear_bit(3, 5)
    assert f._w64 == 64

    # Anti-entropy positions are global, not window-local.
    rows, cols = f.block_data(0)
    assert sorted(cols.tolist()) == [hi - 100, hi]

    # Persistence round-trips narrow: the file stores real containers,
    # and reopen re-derives the same window.
    f.snapshot()
    f.close()
    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    assert f2.row_count(3) == 2
    # The lazy (pre-fault-in) window is container-granular: it covers
    # the high cluster without touching payloads.
    base32, width32 = f2.win32()
    assert base32 * 32 <= hi - 100 and hi < (base32 + width32) * 32
    assert width32 < 2 * WORDS64
    # A full fault-in re-derives the exact word-granular window.
    with f2.mu:
        pass
    assert f2._w64 == 64 and f2._w64_base == WORDS64 - 64
    assert sorted(f2.block_data(0)[1].tolist()) == [hi - 100, hi]
    f2.close()


def test_window_grows_to_cover_mixed_spans(tmp_path):
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    f.set_bit(1, SLICE_WIDTH - 1)      # narrow high window
    f.set_bit(1, 0)                    # now spans the whole slice
    assert f._w64 == WORDS64 and f._w64_base == 0
    assert f.row_count(1) == 2
    assert sorted(f.block_data(0)[1].tolist()) == [0, SLICE_WIDTH - 1]
    f.close()


def test_window_mid_slice_import(tmp_path):
    """A bulk import clustered mid-slice windows around its span and
    serves TopN with a full-width src filter correctly."""
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0,
                 cache_type="ranked").open()
    base_col = 7 * (SLICE_WIDTH // 16)  # container 7
    cols = [base_col + c for c in range(0, 3000, 3)]
    f.import_bits([1] * len(cols), cols)
    f.import_bits([2] * 500, [base_col + c for c in range(500)])
    assert f._w64 < WORDS64 and f._w64_base > 0
    src = np.zeros(WORDS64, dtype=np.uint64)
    for c in cols[:100]:
        src[c >> 6] |= np.uint64(1) << np.uint64(c & 63)
    pairs = f.top(TopOptions(n=2, src=src))
    expect1 = len(set(cols[:100]))
    assert pairs[0] == (1, expect1)
    f.close()


def test_amortized_snapshot_policy(tmp_path):
    """Bulk loading in B equal batches must NOT snapshot per batch
    (the reference's fixed 2000-op cadence rewrites the whole file
    every batch — O(total²) IO); the threshold scales with the
    cardinality at the last snapshot, so rewrites land at
    geometrically growing sizes while the op log stays bounded."""
    import numpy as np

    from pilosa_tpu.storage.fragment import (
        MAX_OPN, OPLOG_MAX_OPS, Fragment,
    )

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    snaps = [0]
    real = f.snapshot

    def counting():
        snaps[0] += 1
        real()

    f.snapshot = counting
    rng = np.random.default_rng(3)
    batches = 24
    per = 6000  # every batch far exceeds the reference cadence of 2000
    for b in range(batches):
        cols = rng.choice(100_000, size=per, replace=False)
        rows = np.full(per, b % 7, dtype=np.uint64)
        f.import_bits(rows, cols.astype(np.uint64))
        limit = max(MAX_OPN, min(f._snap_card // 2, OPLOG_MAX_OPS))
        assert f.op_n <= limit
    # Fixed cadence would snapshot ~24 times; geometric growth keeps it
    # logarithmic in the total.
    assert 1 <= snaps[0] <= 7, snaps[0]

    # Reopen replays the (large) op log correctly.
    counts = {r: int(c) for r, c in zip(f._phys_rows, f._row_counts)}
    f.close()
    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    with f2.mu:
        f2._fault_in_locked()
    assert {r: int(c)
            for r, c in zip(f2._phys_rows, f2._row_counts)} == counts
    f2.close()


def test_snapshot_threshold_resets_on_restore(tmp_path):
    """A backup restore rewrites the file (new snapshot): the
    amortized op-log threshold must follow the RESTORED cardinality,
    not the pre-restore fragment's (review r3: a 10M-bit fragment
    restored to 1k bits must not retain a 4M-op append budget)."""
    import io

    import numpy as np

    from pilosa_tpu.storage.fragment import MAX_OPN, Fragment

    big = Fragment(str(tmp_path / "big"), "i", "f", "standard", 0).open()
    rng = np.random.default_rng(5)
    cols = rng.choice(1_000_000, size=400_000, replace=False)
    big.import_bits(np.zeros(400_000, dtype=np.uint64),
                    cols.astype(np.uint64))
    big.snapshot()
    assert big._snap_card == 400_000

    small = Fragment(str(tmp_path / "small"), "i", "f", "standard",
                     0).open()
    small.import_bits(np.zeros(50, dtype=np.uint64),
                      np.arange(50, dtype=np.uint64))
    buf = io.BytesIO()
    small.write_to(buf)
    buf.seek(0)
    big.read_from(buf)
    assert big._snap_card == 50
    assert not big._op_log_room(MAX_OPN + 1)  # tiny fragment, tiny budget
    small.close()
    big.close()


def test_bsi_import_value_rides_oplog(tmp_path):
    """Chunked BSI value loads append to the op log instead of paying
    a whole-file snapshot per chunk, and values (including overwrites)
    survive close + reopen through last-op-wins replay."""
    import numpy as np

    from pilosa_tpu.storage.fragment import Fragment

    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    # Seed enough cardinality that the amortized threshold has room.
    rng = np.random.default_rng(11)
    seed_cols = rng.choice(500_000, size=60_000, replace=False)
    f.import_bits(np.zeros(60_000, dtype=np.uint64),
                  seed_cols.astype(np.uint64))
    f.snapshot()
    snaps = [0]
    real = f.snapshot
    f.snapshot = lambda: (snaps.__setitem__(0, snaps[0] + 1), real())

    depth = 8
    cols1 = np.arange(1000, dtype=np.uint64)
    vals1 = rng.integers(0, 200, size=1000, dtype=np.uint64)
    f.import_value_bits(cols1, vals1, depth)
    # Second chunk of FRESH columns (disjoint — overwrites snapshot,
    # see test_import_value_overwrite_never_rides_oplog).
    cols2 = np.arange(1000, 1500, dtype=np.uint64)
    vals2 = rng.integers(0, 200, size=500, dtype=np.uint64)
    f.import_value_bits(cols2, vals2, depth)
    assert snaps[0] == 0, "chunked fresh BSI load must not snapshot per call"
    assert f.op_n == (depth + 2) * 1500  # null sandwich + planes per value

    def read_values(frag):
        out = {}
        nn = frag._row_index.get(depth)
        if nn is None:
            return out
        for c in range(1500):
            w, b = c >> 6, c & 63
            if not (frag._matrix[nn][w] >> np.uint64(b)) & np.uint64(1):
                continue
            v = 0
            for i in range(depth):
                p = frag._row_index.get(i)
                if p is not None and (
                        frag._matrix[p][w] >> np.uint64(b)) & np.uint64(1):
                    v |= 1 << i
            out[c] = v
        return out

    want = {int(c): int(v) for c, v in zip(cols1, vals1)}
    want.update({int(c): int(v) for c, v in zip(cols2, vals2)})
    assert read_values(f) == want
    f.close()

    f2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    with f2.mu:
        f2._fault_in_locked()
    assert read_values(f2) == want
    f2.close()


def test_bsi_torn_group_reads_null_not_phantom(tmp_path):
    """A crash can tear a FRESH-insert BSI op-log group at any byte.
    The null sandwich (REMOVE not-null first, ADD not-null last,
    column-major) guarantees the torn column reads as NULL — never as
    a phantom partial value (review r3 atomicity finding). Overwrites
    never reach the op log at all (they snapshot, ADVICE r3) — the
    second half checks that, so a tear can never destroy an
    acknowledged value."""
    import numpy as np

    from pilosa_tpu.roaring.codec import OP_SIZE
    from pilosa_tpu.storage.fragment import Fragment

    depth = 8
    p = str(tmp_path / "frag")
    f = Fragment(p, "i", "f", "standard", 0).open()
    # Seed cardinality so the op-log path engages; snapshot to fix the
    # file base. Column 5 has NO value yet.
    f.import_bits(np.zeros(30_000, dtype=np.uint64),
                  np.arange(30_000, dtype=np.uint64) + 64)
    f.snapshot()
    size_before = __import__("os").path.getsize(p)
    # Fresh insert of value 255 — op-log group of depth+2 records —
    # then tear the group at every possible byte.
    f.import_value_bits(np.array([5], dtype=np.uint64),
                        np.array([255], dtype=np.uint64), depth)
    f.close()
    import os

    full = open(p, "rb").read()
    group_bytes = (depth + 2) * OP_SIZE
    assert os.path.getsize(p) == size_before + group_bytes
    for cut in range(1, group_bytes):  # torn anywhere inside the group
        with open(p, "wb") as out:
            out.write(full[: size_before + cut])
        g = Fragment(p, "i", "f", "standard", 0).open()
        with g.mu:
            g._fault_in_locked()
        val, ok = g.field_value(5, depth)
        # Every tear inside the group reads NULL — even when several
        # plane ADDs are durable, the trailing ADD not-null is not, so
        # no phantom partial value is visible.
        assert not ok, (cut, val)
        g.close()
    # The complete group replays to the inserted value.
    with open(p, "wb") as out:
        out.write(full)
    g = Fragment(p, "i", "f", "standard", 0).open()
    with g.mu:
        g._fault_in_locked()
    assert g.field_value(5, depth) == (255, True)
    # OVERWRITE of the now-acknowledged value: must snapshot, not
    # append — after it the op log is empty and the file carries the
    # new value via atomic rename (old-or-new, never null).
    g.import_value_bits(np.array([5], dtype=np.uint64),
                        np.array([0], dtype=np.uint64), depth)
    assert g.op_n == 0
    g.close()
    h = Fragment(p, "i", "f", "standard", 0).open()
    with h.mu:
        h._fault_in_locked()
    assert h.field_value(5, depth) == (0, True)
    h.close()
